"""SQL lexer + recursive-descent parser.

Reference surface: the flex/bison MySQL grammar + parse nodes
(src/sql/parser/sql_parser_mysql_mode.y, parse_node.h) and the fast parser
used for plan-cache keys (ob_fast_parser.h). The rebuild is a compact
hand-written recursive-descent parser producing sql/ast.py nodes; parameter
extraction for the plan cache is done on the token stream (see
normalize_for_cache) — the fast-parser analog.
"""

from __future__ import annotations

import re
from collections import OrderedDict

from . import ast as A

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<num>\d+\.\d+|\.\d+|\d+)
  | (?P<str>'(?:[^']|'')*')
  | (?P<name>[A-Za-z_][A-Za-z0-9_$]*)
  | (?P<op>->>|->|<>|!=|>=|<=|\|\||[-+*/%(),.;=<>])
    """,
    re.VERBOSE,
)

KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "offset", "as", "and", "or", "not", "in", "between", "like", "is",
    "null", "exists", "case", "when", "then", "else", "end", "cast",
    "extract", "substring", "for", "distinct", "join", "inner", "left",
    "right", "full", "cross", "outer", "on", "date", "interval", "year",
    "month", "day", "asc", "desc", "union", "all", "any", "some", "with",
    "intersect", "except", "over", "partition",
    # window frames
    "rows", "range", "unbounded", "preceding", "following", "current", "row",
    # statements
    "create", "drop", "table", "primary", "key", "if", "insert", "into",
    "values", "update", "set", "delete", "begin", "start", "transaction",
    "commit", "rollback", "alter", "system", "show", "parameters", "tables",
    "lock", "mode", "share", "exclusive", "unique", "index", "kill", "query", "partitions",
    # DCL
    "grant", "revoke", "to", "user", "identified", "privileges",
    # grouping sets
    "rollup", "cube", "grouping", "sets",
    "recursive",
    # materialized views
    "refresh", "materialized", "view",
}


class Token:
    __slots__ = ("kind", "value", "pos")

    def __init__(self, kind, value, pos):
        self.kind = kind  # num | str | name | kw | op | eof
        self.value = value
        self.pos = pos

    def __repr__(self):
        return f"{self.kind}:{self.value}"


def tokenize(sql: str) -> list[Token]:
    out = []
    i = 0
    while i < len(sql):
        m = _TOKEN_RE.match(sql, i)
        if not m:
            raise SyntaxError(f"bad character {sql[i]!r} at {i}")
        i = m.end()
        if m.lastgroup == "ws":
            continue
        v = m.group()
        if m.lastgroup == "name":
            lv = v.lower()
            out.append(Token("kw" if lv in KEYWORDS else "name", lv, m.start()))
        elif m.lastgroup == "str":
            out.append(Token("str", v[1:-1].replace("''", "'"), m.start()))
        elif m.lastgroup == "num":
            out.append(Token("num", v, m.start()))
        else:
            out.append(Token("op", v, m.start()))
    out.append(Token("eof", "", len(sql)))
    return out


def normalize_for_cache(sql: str) -> tuple[str, tuple]:
    """Fast-parser analog: replace literals with ? and collect parameters.
    The normalized text is the plan-cache key (reference: ObPlanCache
    parameterized keys, src/sql/plan_cache)."""
    toks = tokenize(sql)
    parts, params = [], []
    for t in toks:
        if t.kind in ("num", "str"):
            parts.append("?")
            params.append(t.value)
        elif t.kind == "eof":
            break
        else:
            parts.append(t.value)
    return " ".join(parts), tuple(params)


# raw-text memo in front of the tokenizer: serving workloads repeat EXACT
# statement texts (the reference's plan cache is keyed on raw text first),
# and the result is a pure function of the text. Bounded LRU.
_FAST_NORM_MEMO: "OrderedDict[str, tuple]" = OrderedDict()
_FAST_NORM_CAP = 4096


def fast_normalize(sql: str) -> tuple[str, tuple, tuple]:
    """One tokenize pass producing everything the text-keyed fast tier
    needs: a KIND-marked normalized text (?n for numbers, ?s for strings
    — `a = 5` and `a = '5'` plan differently and must not share a text
    entry), the raw literal token texts in order, and their kinds.

    The plain plan-cache key is recoverable without re-tokenizing:
    normalize_for_cache's text is this text with ?n/?s collapsed to ?
    (the tokenizer never emits a bare '?', so the rewrite is unambiguous).
    """
    hit = _FAST_NORM_MEMO.get(sql)
    if hit is not None:
        _FAST_NORM_MEMO.move_to_end(sql)
        return hit
    toks = tokenize(sql)
    parts, params, kinds = [], [], []
    for t in toks:
        if t.kind == "num":
            parts.append("?n")
            params.append(t.value)
            kinds.append("num")
        elif t.kind == "str":
            parts.append("?s")
            params.append(t.value)
            kinds.append("str")
        elif t.kind == "eof":
            break
        else:
            parts.append(t.value)
    out = (" ".join(parts), tuple(params), tuple(kinds))
    _FAST_NORM_MEMO[sql] = out
    if len(_FAST_NORM_MEMO) > _FAST_NORM_CAP:
        _FAST_NORM_MEMO.popitem(last=False)
    return out


def digest_text(sql: str) -> str:
    """Statement digest for the workload repository: the kind-marked
    normalized text (identical to the fast tier's key, so fast-path
    statements and their full-path compiles share one digest). Statements
    the tokenizer rejects still need SOME stable digest — whitespace
    collapse keeps repeats folding together without claiming kinds."""
    try:
        return fast_normalize(sql)[0]
    except Exception:  # noqa: BLE001 - any tokenizer error
        return " ".join(sql.split())


class Parser:
    def __init__(self, sql: str):
        self.sql = sql
        self.toks = tokenize(sql)
        self.i = 0

    # -- token helpers --------------------------------------------------
    def peek(self, k=0) -> Token:
        return self.toks[min(self.i + k, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.toks[self.i]
        self.i += 1
        return t

    def accept(self, value: str) -> bool:
        t = self.peek()
        if t.kind in ("kw", "op") and t.value == value:
            self.i += 1
            return True
        return False

    def expect(self, value: str) -> Token:
        t = self.next()
        if t.value != value:
            raise SyntaxError(f"expected {value!r}, got {t.value!r} @{t.pos}")
        return t

    # -- entry ----------------------------------------------------------
    def parse_statement(self) -> A.Node:
        """Any statement: SELECT (incl. WITH), DDL, DML, tx control."""
        t = self.peek()
        handlers = {
            "create": self._create,
            "drop": self._drop,
            "insert": self._insert,
            "update": self._update,
            "delete": self._delete,
            "begin": self._tx_begin,
            "start": self._tx_begin,
            "commit": lambda: (self.next(), A.Commit())[1],
            "rollback": lambda: (self.next(), A.Rollback())[1],
            "alter": self._alter,
            "show": self._show,
            "lock": self._lock,
            "kill": self._kill,
            "grant": self._grant,
            "revoke": self._revoke,
            "refresh": self._refresh,
        }
        h = handlers.get(t.value) if t.kind == "kw" else None
        if h is None:
            return self.parse()
        stmt = h()
        self.accept(";")
        if self.peek().kind != "eof":
            tk = self.peek()
            raise SyntaxError(f"trailing tokens at {tk.pos}: {tk.value!r}")
        return stmt

    def _alter(self) -> "A.AlterSystemSet | A.RunLayoutAdvisor":
        self.expect("alter")
        self.expect("system")
        if self.peek().value == "run":
            self.next()
            self.expect("layout")
            self.expect("advisor")
            return A.RunLayoutAdvisor()
        self.expect("set")
        name = self.next().value
        self.expect("=")
        t = self.peek()
        if t.kind == "str":
            self.next()
            return A.AlterSystemSet(name, t.value)
        # unquoted value: take the RAW statement text (case preserved, so
        # WARN stays WARN; suffixed values like 32M / 10s lex as several
        # tokens but are one value)
        start = t.pos
        end = start
        while self.peek().kind != "eof" and self.peek().value != ";":
            tk = self.next()
            end = tk.pos + len(str(tk.value))
        if end == start:
            raise SyntaxError(f"missing parameter value at {t.pos}")
        return A.AlterSystemSet(name, self.sql[start:end].strip())

    def _kill(self) -> "A.KillQuery":
        self.expect("kill")
        self.accept("query")
        return A.KillQuery(int(self.next().value))

    def _lock(self) -> A.LockTable:
        self.expect("lock")
        self.expect("table")
        name = self.next().value
        self.expect("in")
        t = self.next().value
        if t not in ("share", "exclusive"):
            raise SyntaxError(f"bad lock mode {t!r}")
        self.expect("mode")
        return A.LockTable(name, exclusive=(t == "exclusive"))

    def _show(self) -> A.Show:
        self.expect("show")
        what = self.next().value
        like = None
        if self.accept("like"):
            like = self.next().value
        return A.Show(what, like)

    def _tx_begin(self) -> A.Begin:
        if self.next().value == "start":
            self.expect("transaction")
        return A.Begin()

    def _privlist(self) -> tuple[str, ...]:
        privs = [self.next().value.lower()]
        if privs[0] == "all":
            self.accept("privileges")
        while self.accept(","):
            privs.append(self.next().value.lower())
        return tuple(privs)

    def _grant(self) -> "A.Grant":
        self.expect("grant")
        privs = self._privlist()
        self.expect("on")
        obj = "*" if self.accept("*") else self.next().value
        self.expect("to")
        return A.Grant(privs, obj, self.next().value)

    def _revoke(self) -> "A.Revoke":
        self.expect("revoke")
        privs = self._privlist()
        self.expect("on")
        obj = "*" if self.accept("*") else self.next().value
        self.expect("from")
        return A.Revoke(privs, obj, self.next().value)

    def _create(self) -> "A.CreateTable | A.CreateIndex":
        self.expect("create")
        if self.peek().value == "materialized":
            self.next()
            if self.next().value != "view":
                raise SyntaxError("expected MATERIALIZED VIEW")
            name = self.next().value
            t = self.expect("as")
            # the defining query is kept as TEXT (re-planned per refresh
            # against the current schema, like the reference's mview
            # definitions in the schema service); consume to EOF
            self.i = len(self.toks) - 1
            return A.CreateMaterializedView(
                name, self.sql[t.pos + 2:].strip().rstrip(";")
            )
        if self.peek().value == "view" or (
            self.peek().value == "or" and self.peek(1).value == "replace"
        ):
            replace = False
            if self.peek().value == "or":
                self.next()
                self.next()
                replace = True
            if self.next().value != "view":
                raise SyntaxError("expected VIEW")
            name = self.next().value
            t = self.expect("as")
            self.i = len(self.toks) - 1  # definition kept as text
            return A.CreateView(
                name, self.sql[t.pos + 2:].strip().rstrip(";"), replace
            )
        if self.peek().value == "trigger":
            self.next()
            name = self.next().value
            timing = self.next().value
            if timing not in ("before", "after"):
                raise SyntaxError("expected BEFORE or AFTER")
            event = self.next().value
            if event not in ("insert", "update", "delete"):
                raise SyntaxError("expected INSERT, UPDATE or DELETE")
            self.expect("on")
            table = self.next().value
            if self.next().value != "for":
                raise SyntaxError("expected FOR EACH ROW")
            if self.next().value != "each":
                raise SyntaxError("expected FOR EACH ROW")
            t = self.next()
            if t.value != "row":
                raise SyntaxError("expected FOR EACH ROW")
            self.i = len(self.toks) - 1  # body kept as text
            body = self.sql[t.pos + 3:].strip().rstrip(";")
            return A.CreateTrigger(name, timing, event, table, body)
        if self.peek().value == "external":
            self.next()
            self.expect("table")
            name = self.next().value
            if self.next().value != "using":
                raise SyntaxError("expected USING <format>")
            fmt = self.next().value
            if self.next().value != "location":
                raise SyntaxError("expected LOCATION '<path>'")
            t = self.next()
            if t.kind != "str":
                raise SyntaxError("LOCATION needs a quoted path")
            return A.CreateExternalTable(name, fmt, t.value)
        if self.peek().value == "vector" and self.peek(1).value == "index":
            self.next()
            self.next()
            name = self.next().value
            self.expect("on")
            table = self.next().value
            self.expect("(")
            column = self.next().value
            self.expect(")")
            lists, nprobe = 0, 8
            if self.peek().value == "with":
                self.next()
                self.expect("(")
                while True:
                    k = self.next().value
                    self.expect("=")
                    v = int(self.next().value)
                    if k == "lists":
                        lists = v
                    elif k == "nprobe":
                        nprobe = v
                    else:
                        raise SyntaxError(f"unknown vector index option {k}")
                    if not self.accept(","):
                        break
                self.expect(")")
            return A.CreateVectorIndex(name, table, column, lists, nprobe)
        if self.accept("user"):
            name = self.next().value
            pw = ""
            if self.accept("identified"):
                self.expect("by")
                t = self.next()
                pw = t.value
            return A.CreateUser(name, pw)
        unique = self.accept("unique")
        if self.accept("index"):
            if_not_exists = False
            if self.accept("if"):
                self.expect("not")
                self.expect("exists")
                if_not_exists = True
            name = self.next().value
            self.expect("on")
            table = self.next().value
            self.expect("(")
            cols = [self.next().value]
            while self.accept(","):
                cols.append(self.next().value)
            self.expect(")")
            return A.CreateIndex(name, table, tuple(cols), unique, if_not_exists)
        if unique:
            raise SyntaxError("UNIQUE outside CREATE UNIQUE INDEX")
        self.expect("table")
        if_not_exists = False
        if self.accept("if"):
            self.expect("not")
            self.expect("exists")
            if_not_exists = True
        name = self.next().value
        self.expect("(")
        cols: list[A.ColumnDef] = []
        pk: tuple[str, ...] = ()
        while True:
            if self.peek().value == "primary":
                self.next()
                self.expect("key")
                self.expect("(")
                pkl = [self.next().value]
                while self.accept(","):
                    pkl.append(self.next().value)
                self.expect(")")
                pk = tuple(pkl)
            else:
                cname = self.next().value
                tname = self.type_name()
                not_null = False
                if self.accept("not"):
                    self.expect("null")
                    not_null = True
                elif self.accept("null"):
                    pass
                if self.accept("primary"):
                    self.expect("key")
                    pk = (cname,)
                cols.append(A.ColumnDef(cname, tname, not_null))
            if not self.accept(","):
                break
        self.expect(")")
        part_col, n_parts = None, 1
        if self.accept("partition"):
            self.expect("by")
            kind = self.next().value
            if kind != "hash":
                raise SyntaxError(f"unsupported partitioning {kind!r}")
            self.expect("(")
            part_col = self.next().value
            self.expect(")")
            self.expect("partitions")
            n_parts = int(self.next().value)
            if n_parts < 1:
                raise SyntaxError("PARTITIONS must be >= 1")
        return A.CreateTable(
            name, tuple(cols), pk, if_not_exists, part_col, n_parts
        )

    def _refresh(self) -> "A.RefreshMaterializedView":
        self.expect("refresh")
        if self.next().value != "materialized":
            raise SyntaxError("expected REFRESH MATERIALIZED VIEW")
        if self.next().value != "view":
            raise SyntaxError("expected REFRESH MATERIALIZED VIEW")
        return A.RefreshMaterializedView(self.next().value)

    def _drop(self) -> "A.DropTable | A.DropIndex":
        self.expect("drop")
        if self.peek().value == "materialized":
            self.next()
            if self.next().value != "view":
                raise SyntaxError("expected MATERIALIZED VIEW")
            return A.DropMaterializedView(self.next().value)
        if self.peek().value == "view":
            self.next()
            return A.DropView(self.next().value)
        if self.peek().value == "trigger":
            self.next()
            return A.DropTrigger(self.next().value)
        if self.peek().value == "vector" and self.peek(1).value == "index":
            self.next()
            self.next()
            name = self.next().value
            self.expect("on")
            table = self.next().value
            self.expect("(")
            column = self.next().value
            self.expect(")")
            return A.DropVectorIndex(name, table, column)
        if self.accept("user"):
            return A.DropUser(self.next().value)
        if self.accept("index"):
            if_exists = False
            if self.accept("if"):
                self.expect("exists")
                if_exists = True
            name = self.next().value
            self.expect("on")
            return A.DropIndex(name, self.next().value, if_exists)
        self.expect("table")
        if_exists = False
        if self.accept("if"):
            self.expect("exists")
            if_exists = True
        return A.DropTable(self.next().value, if_exists)

    def _insert(self) -> A.Insert:
        self.expect("insert")
        self.expect("into")
        name = self.next().value
        columns: tuple[str, ...] = ()
        if self.peek().value == "(":
            self.next()
            cl = [self.next().value]
            while self.accept(","):
                cl.append(self.next().value)
            self.expect(")")
            columns = tuple(cl)
        if self.accept("values"):
            rows = []
            while True:
                self.expect("(")
                row = [self.expr()]
                while self.accept(","):
                    row.append(self.expr())
                self.expect(")")
                rows.append(tuple(row))
                if not self.accept(","):
                    break
            return A.Insert(name, columns, tuple(rows))
        # INSERT ... SELECT
        return A.Insert(name, columns, (), self.select())

    def _update(self) -> A.Update:
        self.expect("update")
        name = self.next().value
        self.expect("set")
        assigns = []
        while True:
            col = self.next().value
            self.expect("=")
            assigns.append((col, self.expr()))
            if not self.accept(","):
                break
        where = self.expr() if self.accept("where") else None
        return A.Update(name, tuple(assigns), where)

    def _delete(self) -> A.Delete:
        self.expect("delete")
        self.expect("from")
        name = self.next().value
        where = self.expr() if self.accept("where") else None
        return A.Delete(name, where)

    def parse(self) -> "A.Select | A.SetSelect":
        ctes = []
        recursive = False
        if self.accept("with"):
            recursive = self.accept("recursive")
            while True:
                name = self.next().value
                self.expect("as")
                self.expect("(")
                # recursive bodies are base UNION [ALL] step: full
                # query expressions, not bare SELECTs
                ctes.append((name, self.query_expr()))
                self.expect(")")
                if not self.accept(","):
                    break
        s = self.query_expr()
        if ctes:
            rec_names = tuple(n for n, _ in ctes) if recursive else ()
            if isinstance(s, A.SetSelect):
                s = A.SetSelect(
                    kind=s.kind, all=s.all, left=s.left, right=s.right,
                    order_by=s.order_by, limit=s.limit, offset=s.offset,
                    ctes=tuple(ctes), recursive_ctes=rec_names,
                )
            else:
                s = A.Select(
                    items=s.items, from_=s.from_, where=s.where,
                    group_by=s.group_by, having=s.having, order_by=s.order_by,
                    limit=s.limit, offset=s.offset, distinct=s.distinct,
                    ctes=tuple(ctes), recursive_ctes=rec_names,
                    group_sets=s.group_sets,
                )
        self.accept(";")
        if self.peek().kind != "eof":
            t = self.peek()
            raise SyntaxError(f"trailing tokens at {t.pos}: {t.value!r}")
        return s

    # -- set operations (UNION / INTERSECT / EXCEPT) --------------------
    def query_expr(self) -> "A.Select | A.SetSelect":
        left, lparen = self.query_term()
        while self.peek().kind == "kw" and self.peek().value in ("union", "except"):
            kind = self.next().value
            all_ = self.accept("all")
            self.accept("distinct")
            right, rparen = self.query_term()
            left = self._make_setop(kind, all_, left, lparen, right, rparen)
            lparen = False
        # trailing ORDER BY / LIMIT after a parenthesized last branch still
        # sits in the token stream; it scopes to the whole set result
        if isinstance(left, A.SetSelect):
            order_by = list(left.order_by)
            limit, offset = left.limit, left.offset
            changed = False
            if self.peek().kind == "kw" and self.peek().value == "order":
                if order_by:
                    raise SyntaxError("duplicate ORDER BY on set operation")
                self.next()
                self.expect("by")
                order_by = [self.order_item()]
                while self.accept(","):
                    order_by.append(self.order_item())
                changed = True
            if self.peek().kind == "kw" and self.peek().value == "limit":
                if limit is not None:
                    raise SyntaxError("duplicate LIMIT on set operation")
                self.next()
                limit = int(self.next().value)
                if self.accept("offset"):
                    offset = int(self.next().value)
                changed = True
            if changed:
                left = A.SetSelect(
                    left.kind, left.all, left.left, left.right,
                    tuple(order_by), limit, offset, left.ctes,
                )
        return left

    def query_term(self):
        left, lparen = self.query_primary()
        while self.peek().kind == "kw" and self.peek().value == "intersect":
            self.next()
            all_ = self.accept("all")
            self.accept("distinct")
            right, rparen = self.query_primary()
            left = self._make_setop("intersect", all_, left, lparen, right, rparen)
            lparen = False
        return left, lparen

    def query_primary(self):
        if self.peek().value == "(" and self.peek().kind == "op":
            self.next()
            q = self.query_expr()
            self.expect(")")
            return q, True
        return self.select(), False

    @staticmethod
    def _make_setop(kind, all_, left, lparen, right, rparen):
        """Combine two branches. A trailing ORDER BY / LIMIT greedily parsed
        into an UNPARENTHESIZED right branch scopes to the whole set result
        (SQL scoping) and hoists onto the SetSelect node — including from a
        nested SetSelect built by a tighter-binding INTERSECT. Parenthesized
        branches keep their clauses (branch-local top-N is legitimate)."""
        order_by, limit, offset = (), None, None
        if (
            not lparen
            and isinstance(left, A.Select)
            and (left.order_by or left.limit is not None)
        ):
            raise SyntaxError(
                "ORDER BY/LIMIT on a set-operation branch needs parentheses"
            )
        if not rparen and isinstance(right, A.Select) and (
            right.order_by or right.limit is not None
        ):
            order_by, limit, offset = right.order_by, right.limit, right.offset
            right = A.Select(
                items=right.items, from_=right.from_, where=right.where,
                group_by=right.group_by, having=right.having,
                distinct=right.distinct, ctes=right.ctes,
            )
        elif not rparen and isinstance(right, A.SetSelect) and (
            right.order_by or right.limit is not None
        ):
            order_by, limit, offset = right.order_by, right.limit, right.offset
            right = A.SetSelect(
                right.kind, right.all, right.left, right.right,
                (), None, None, right.ctes,
            )
        return A.SetSelect(kind, all_, left, right, order_by, limit, offset)

    def select(self) -> A.Select:
        self.expect("select")
        distinct = self.accept("distinct")
        items = [self.select_item()]
        while self.accept(","):
            items.append(self.select_item())
        from_ = ()
        if self.accept("from"):
            from_ = [self.table_expr()]
            while self.accept(","):
                from_.append(self.table_expr())
        where = self.expr() if self.accept("where") else None
        group_by = ()
        group_sets = None
        if self.accept("group"):
            self.expect("by")
            if self.peek().kind == "kw" and self.peek().value in (
                "rollup", "cube"
            ):
                kind = self.next().value
                self.expect("(")
                group_by = [self.expr()]
                while self.accept(","):
                    group_by.append(self.expr())
                self.expect(")")
                k = len(group_by)
                if kind == "rollup":
                    group_sets = tuple(
                        tuple(range(k - i)) for i in range(k + 1)
                    )
                else:  # cube: all subsets, largest first
                    group_sets = tuple(sorted(
                        (tuple(i for i in range(k) if m & (1 << i))
                         for m in range(1 << k)),
                        key=lambda s: (-len(s), s),
                    ))
            elif self.peek().kind == "kw" and self.peek().value == "grouping":
                self.next()
                self.expect("sets")
                self.expect("(")
                sets_ast: list[list] = []
                while True:
                    self.expect("(")
                    one: list = []
                    if not self.accept(")"):
                        one.append(self.expr())
                        while self.accept(","):
                            one.append(self.expr())
                        self.expect(")")
                    sets_ast.append(one)
                    if not self.accept(","):
                        break
                self.expect(")")
                group_by = []
                sets_idx = []
                for one in sets_ast:
                    idxs = []
                    for e in one:
                        if e not in group_by:
                            group_by.append(e)
                        idxs.append(group_by.index(e))
                    sets_idx.append(tuple(idxs))
                group_sets = tuple(sets_idx)
            else:
                group_by = [self.expr()]
                while self.accept(","):
                    group_by.append(self.expr())
        having = self.expr() if self.accept("having") else None
        order_by = []
        if self.accept("order"):
            self.expect("by")
            order_by = [self.order_item()]
            while self.accept(","):
                order_by.append(self.order_item())
        limit = offset = None
        if self.accept("limit"):
            limit = int(self.next().value)
            if self.accept("offset"):
                offset = int(self.next().value)
        return A.Select(
            items=tuple(items),
            from_=tuple(from_),
            where=where,
            group_by=tuple(group_by),
            having=having,
            order_by=tuple(order_by),
            limit=limit,
            offset=offset,
            distinct=distinct,
            group_sets=group_sets,
        )

    def select_item(self) -> A.SelectItem:
        if self.peek().value == "*" and self.peek().kind == "op":
            self.next()
            return A.SelectItem(A.Star())
        e = self.expr()
        alias = None
        if self.accept("as"):
            alias = self.next().value
        elif self.peek().kind == "name":
            alias = self.next().value
        return A.SelectItem(e, alias)

    def order_item(self) -> A.OrderItem:
        e = self.expr()
        desc = False
        if self.accept("desc"):
            desc = True
        else:
            self.accept("asc")
        return A.OrderItem(e, desc)

    # -- FROM -----------------------------------------------------------
    def table_expr(self) -> A.Node:
        left = self.table_primary()
        while True:
            kind = None
            if self.accept("inner"):
                kind = "inner"
            elif self.accept("left"):
                self.accept("outer")
                kind = "left"
            elif self.accept("right"):
                self.accept("outer")
                kind = "right"
            elif self.accept("full"):
                self.accept("outer")
                kind = "full"
            elif self.accept("cross"):
                kind = "cross"
            elif self.peek().value == "join":
                kind = "inner"
            if kind is None:
                return left
            self.expect("join")
            right = self.table_primary()
            on = None
            if kind != "cross" and self.accept("on"):
                on = self.expr()
            left = A.Join(kind, left, right, on)

    def table_primary(self) -> A.Node:
        if self.accept("("):
            sub = self.select()
            self.expect(")")
            self.accept("as")
            alias = self.next().value
            return A.SubqueryRef(sub, alias)
        name = self.next()
        if name.kind not in ("name", "kw"):
            raise SyntaxError(f"expected table name, got {name.value!r}")
        alias = None
        snapshot = None
        if self.accept("as"):
            if self.peek().value == "of":
                # FLASHBACK: t AS OF SNAPSHOT <ts> [alias]
                self.next()
                if self.next().value != "snapshot":
                    raise SyntaxError("expected AS OF SNAPSHOT <ts>")
                snapshot = int(self.next().value)
                if self.accept("as"):
                    alias = self.next().value
                elif self.peek().kind == "name":
                    alias = self.next().value
            else:
                alias = self.next().value
        elif self.peek().kind == "name":
            alias = self.next().value
        return A.TableRef(name.value, alias, snapshot)

    # -- expressions ----------------------------------------------------
    def expr(self) -> A.Node:
        return self.or_expr()

    def or_expr(self) -> A.Node:
        e = self.and_expr()
        while self.accept("or"):
            e = A.BinOp("or", e, self.and_expr())
        return e

    def and_expr(self) -> A.Node:
        e = self.not_expr()
        while self.accept("and"):
            e = A.BinOp("and", e, self.not_expr())
        return e

    def not_expr(self) -> A.Node:
        if self.accept("not"):
            return A.UnaryOp("not", self.not_expr())
        return self.predicate()

    def predicate(self) -> A.Node:
        e = self.additive()
        negated = False
        if self.peek().value == "not" and self.peek(1).value in (
            "between", "in", "like",
        ):
            self.next()
            negated = True
        t = self.peek()
        if t.kind == "op" and t.value in ("=", "!=", "<>", "<", "<=", ">", ">="):
            self.next()
            # ANY/ALL/SOME subquery comparisons
            if self.peek().value in ("any", "all", "some"):
                raise NotImplementedError("quantified comparisons")
            return A.BinOp(t.value, e, self.additive())
        if self.accept("between"):
            low = self.additive()
            self.expect("and")
            high = self.additive()
            return A.BetweenOp(e, low, high, negated)
        if self.accept("in"):
            self.expect("(")
            if self.peek().value == "select":
                sub = self.select()
                self.expect(")")
                return A.InOp(e, None, sub, negated)
            items = [self.expr()]
            while self.accept(","):
                items.append(self.expr())
            self.expect(")")
            return A.InOp(e, tuple(items), None, negated)
        if self.accept("like"):
            return A.LikeOp(e, self.additive(), negated)
        if self.accept("is"):
            neg = self.accept("not")
            t2 = self.peek()
            if t2.kind == "name" and t2.value == "json":
                # x IS [NOT] JSON -> json_valid(x) (the SQL/JSON predicate;
                # MySQL spells it json_valid, Oracle IS JSON)
                self.next()
                f = A.FuncCall("json_valid", (e,))
                return A.UnaryOp("not", f) if neg else f
            self.expect("null")
            return A.IsNullOp(e, neg)
        return e

    def additive(self) -> A.Node:
        e = self.multiplicative()
        while True:
            t = self.peek()
            if t.kind == "op" and t.value in ("+", "-"):
                self.next()
                e = A.BinOp(t.value, e, self.multiplicative())
            else:
                return e

    def multiplicative(self) -> A.Node:
        e = self.unary()
        while True:
            t = self.peek()
            if t.kind == "op" and t.value in ("*", "/", "%"):
                self.next()
                e = A.BinOp(t.value, e, self.unary())
            else:
                return e

    def unary(self) -> A.Node:
        if self.peek().value == "-" and self.peek().kind == "op":
            self.next()
            return A.UnaryOp("-", self.unary())
        if self.peek().value == "+" and self.peek().kind == "op":
            self.next()
            return self.unary()
        return self._postfix(self.atom())

    def _postfix(self, e: A.Node) -> A.Node:
        """MySQL JSON arrow operators: col->'$.p' = json_extract,
        col->>'$.p' = json_unquote(json_extract)."""
        while self.peek().kind == "op" and self.peek().value in ("->", "->>"):
            op = self.next().value
            t = self.next()
            if t.kind != "str":
                raise SyntaxError(
                    f"JSON path string expected after {op} @{t.pos}")
            ex = A.FuncCall("json_extract", (e, A.StringLit(t.value)))
            e = ex if op == "->" else A.FuncCall("json_unquote", (ex,))
        return e

    def atom(self) -> A.Node:
        t = self.peek()
        if t.kind == "num":
            self.next()
            return A.NumberLit(t.value)
        if t.kind == "str":
            self.next()
            return A.StringLit(t.value)
        if t.value == "(":
            self.next()
            if self.peek().value == "select":
                sub = self.select()
                self.expect(")")
                return A.ScalarSubquery(sub)
            e = self.expr()
            self.expect(")")
            return e
        if t.value == "date" and self.peek(1).kind == "str":
            self.next()
            return A.DateLit(self.next().value)
        if t.value == "interval":
            self.next()
            v = self.next().value  # quoted or bare number
            unit = self.next().value
            return A.IntervalLit(str(v), unit)
        if t.value == "exists":
            self.next()
            self.expect("(")
            sub = self.select()
            self.expect(")")
            return A.ExistsOp(sub)
        if t.value == "case":
            return self.case_expr()
        if t.value == "cast":
            self.next()
            self.expect("(")
            e = self.expr()
            self.expect("as")
            tn = self.type_name()
            self.expect(")")
            return A.CastOp(e, tn)
        if t.value == "extract":
            self.next()
            self.expect("(")
            fld = self.next().value
            self.expect("from")
            e = self.expr()
            self.expect(")")
            return A.ExtractOp(fld, e)
        if t.value == "substring":
            self.next()
            self.expect("(")
            e = self.expr()
            if self.accept("from"):
                start = self.expr()
                length = self.expr() if self.accept("for") else None
            else:
                self.expect(",")
                start = self.expr()
                length = self.expr() if self.accept(",") else None
            self.expect(")")
            return A.SubstringOp(e, start, length)
        if t.kind in ("name", "kw"):
            self.next()
            # function call?
            if self.peek().value == "(" and self.peek().kind == "op":
                self.next()
                distinct = self.accept("distinct")
                if self.peek().value == "*" and self.peek().kind == "op":
                    self.next()
                    args = (A.Star(),)
                else:
                    args = []
                    if self.peek().value != ")":
                        args = [self.expr()]
                        while self.accept(","):
                            args.append(self.expr())
                    args = tuple(args)
                self.expect(")")
                if self.peek().value == "over" and self.peek().kind == "kw":
                    self.next()
                    self.expect("(")
                    partition_by = []
                    if self.accept("partition"):
                        self.expect("by")
                        partition_by = [self.expr()]
                        while self.accept(","):
                            partition_by.append(self.expr())
                    order_by = []
                    if self.accept("order"):
                        self.expect("by")
                        order_by = [self.order_item()]
                        while self.accept(","):
                            order_by.append(self.order_item())
                    frame = self._frame_clause()
                    self.expect(")")
                    if distinct:
                        raise SyntaxError("DISTINCT window aggregates unsupported")
                    return A.WindowCall(
                        t.value, args, tuple(partition_by), tuple(order_by),
                        frame,
                    )
                return A.FuncCall(t.value, args, distinct)
            parts = [t.value]
            while self.peek().value == "." and self.peek().kind == "op":
                self.next()
                parts.append(self.next().value)
            return A.Name(tuple(parts))
        raise SyntaxError(f"unexpected token {t.value!r} @{t.pos}")

    def _frame_clause(self):
        """[ROWS|RANGE [BETWEEN <bound> AND <bound> | <bound>]] inside an
        OVER(). Returns (unit, lo, hi) or None; bounds are signed row/value
        offsets (negative = PRECEDING), 0 = CURRENT ROW, None = UNBOUNDED
        toward that end."""
        if self.peek().kind != "kw" or self.peek().value not in ("rows", "range"):
            return None
        unit = self.next().value

        def bound(direction_required=None):
            if self.accept("unbounded"):
                d = self.next().value  # preceding | following
                if d not in ("preceding", "following"):
                    raise SyntaxError(f"UNBOUNDED {d.upper()}?")
                return None, d
            if self.accept("current"):
                self.expect("row")
                return 0, "current"
            n = self.next()
            if n.kind != "num":
                raise SyntaxError(f"frame bound needs a number, got {n.value!r}")
            k = int(n.value)
            d = self.next().value
            if d == "preceding":
                return -k, d
            if d == "following":
                return k, d
            raise SyntaxError(f"frame bound direction {d!r}")

        if self.accept("between"):
            lo, lod = bound()
            self.expect("and")
            hi, hid = bound()
        else:
            lo, lod = bound()
            if lod == "following":
                raise SyntaxError("frame start cannot be FOLLOWING without BETWEEN")
            hi, hid = 0, "current"
        if lod == "following" and lo is None:
            raise SyntaxError("frame start cannot be UNBOUNDED FOLLOWING")
        if hid == "preceding" and hi is None:
            raise SyntaxError("frame end cannot be UNBOUNDED PRECEDING")
        # normalize UNBOUNDED: start-side None means -inf, end-side +inf
        return (unit, lo, hi)

    def case_expr(self) -> A.Node:
        self.expect("case")
        whens = []
        while self.accept("when"):
            c = self.expr()
            self.expect("then")
            v = self.expr()
            whens.append((c, v))
        default = self.expr() if self.accept("else") else None
        self.expect("end")
        return A.CaseOp(tuple(whens), default)

    def type_name(self) -> str:
        base = self.next().value
        if self.accept("("):
            args = [self.next().value]
            while self.accept(","):
                args.append(self.next().value)
            self.expect(")")
            return f"{base}({','.join(args)})"
        return base


def parse_statement(sql: str) -> A.Node:
    """Parse any statement (SELECT, DDL, DML, tx control)."""
    return Parser(sql).parse_statement()


def parse(sql: str) -> A.Select:
    return Parser(sql).parse()
