"""PL: stored procedures — parser + host interpreter.

Reference surface: src/pl (ObPLResolver/ObPLExecutor — OceanBase's
159k-line PL/SQL layer) and src/objit (its LLVM JIT). The rebuild keeps
the architectural split the reference has, at this engine's scale:

- CONTROL FLOW is host-side (a tree-walking interpreter over the
  procedure AST — the reference interprets or JITs it; either way it is
  scalar host work),
- every SQL STATEMENT inside a body executes through the session's
  normal dispatch, so it rides the plan cache — and the plan cache's
  artifact IS a compiled XLA executable. That is this engine's
  equivalent of objit: the hot data-parallel parts of a procedure are
  jitted machine code on the accelerator; only the scalar glue walks
  the tree.

Grammar (MySQL-flavored subset):

  CREATE PROCEDURE name ([IN|OUT|INOUT] p type, ...) BEGIN body END
  body:  DECLARE v type [DEFAULT expr] ;
         SET v = expr ;
         IF expr THEN body [ELSEIF expr THEN body]* [ELSE body] END IF ;
         WHILE expr DO body END WHILE ;
         RETURN [expr] ;
         CALL name(args) ;
         <any SQL statement> [INTO v, ...] ;

Variables substitute into embedded SQL as literals at execution (the
statement text itself was parsed once at CREATE; substitution is an AST
rewrite, so plans parameterize and re-use exactly like client SQL).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from . import ast as A
from .parser import Parser, tokenize


class PlError(Exception):
    pass


# ---------------------------------------------------------------- AST

@dataclass(frozen=True)
class PlParam:
    mode: str  # in | out | inout
    name: str
    type_name: str


@dataclass(frozen=True)
class PlProcedure:
    name: str
    params: tuple[PlParam, ...]
    body: tuple  # of Pl* statements
    text: str    # original definition (SHOW/replication surface)


@dataclass(frozen=True)
class PlDeclare:
    name: str
    type_name: str
    default: A.Node | None


@dataclass(frozen=True)
class PlSet:
    name: str
    expr: A.Node


@dataclass(frozen=True)
class PlIf:
    branches: tuple[tuple[A.Node, tuple], ...]  # (cond, body)*
    orelse: tuple


@dataclass(frozen=True)
class PlWhile:
    cond: A.Node
    body: tuple


@dataclass(frozen=True)
class PlReturn:
    expr: A.Node | None


@dataclass(frozen=True)
class PlCall:
    name: str
    args: tuple[A.Node, ...]


@dataclass(frozen=True)
class PlSql:
    stmt: object          # parsed statement AST
    into: tuple[str, ...]  # SELECT ... INTO targets (empty otherwise)


# ------------------------------------------------------------- parser

class PlParser(Parser):
    """Extends the SQL parser with the procedure grammar (shares the
    lexer, expression grammar and statement parsers)."""

    def parse_procedure(self) -> PlProcedure:
        self.expect("create")
        if self.next().value != "procedure":
            raise SyntaxError("expected CREATE PROCEDURE")
        name = self.next().value
        params: list[PlParam] = []
        self.expect("(")
        if not self.accept(")"):
            while True:
                mode = "in"
                if self.peek().value in ("in", "out", "inout"):
                    mode = self.next().value
                pname = self.next().value
                ptype = self.type_name()
                params.append(PlParam(mode, pname, ptype))
                if not self.accept(","):
                    break
            self.expect(")")
        body = self._block()
        return PlProcedure(name, tuple(params), body, self.sql)

    def _block(self) -> tuple:
        self.expect("begin")
        out: list = []
        while not self.accept("end"):
            out.append(self._pl_statement())
        return tuple(out)

    def _pl_statement(self):
        t = self.peek()
        v = t.value
        if v == "declare":
            self.next()
            name = self.next().value
            tname = self.type_name()
            dflt = None
            if self.peek().value == "default":
                self.next()
                dflt = self.expr_node()
            self.expect(";")
            return PlDeclare(name, tname, dflt)
        if v == "set":
            self.next()
            name = self.next().value
            self.expect("=")
            e = self.expr_node()
            self.expect(";")
            return PlSet(name, e)
        if v == "if":
            self.next()
            branches = []
            cond = self.expr_node()
            if self.next().value != "then":
                raise SyntaxError("expected THEN")
            body = self._stmts_until("elseif", "else", "end")
            branches.append((cond, body))
            orelse: tuple = ()
            while True:
                nxt = self.next().value
                if nxt == "elseif":
                    c2 = self.expr_node()
                    if self.next().value != "then":
                        raise SyntaxError("expected THEN")
                    branches.append(
                        (c2, self._stmts_until("elseif", "else", "end")))
                elif nxt == "else":
                    orelse = self._stmts_until("end")
                elif nxt == "end":
                    if self.next().value != "if":
                        raise SyntaxError("expected END IF")
                    self.expect(";")
                    break
                else:
                    raise SyntaxError(f"unexpected {nxt!r} in IF")
            return PlIf(tuple(branches), orelse)
        if v == "while":
            self.next()
            cond = self.expr_node()
            if self.next().value != "do":
                raise SyntaxError("expected DO")
            body = self._stmts_until("end")
            self.next()  # end
            if self.next().value != "while":
                raise SyntaxError("expected END WHILE")
            self.expect(";")
            return PlWhile(cond, body)
        if v == "return":
            self.next()
            e = None
            if self.peek().value != ";":
                e = self.expr_node()
            self.expect(";")
            return PlReturn(e)
        if v == "call":
            self.next()
            name = self.next().value
            args: list = []
            self.expect("(")
            if not self.accept(")"):
                args.append(self.expr_node())
                while self.accept(","):
                    args.append(self.expr_node())
                self.expect(")")
            self.expect(";")
            return PlCall(name, tuple(args))
        # otherwise: one embedded SQL statement up to ';' (re-lexed so
        # the statement parsers see a clean stream)
        start = t.pos
        depth = 0
        toks: list = []  # (token, paren depth) — for token-level INTO strip
        while True:
            tok = self.peek()
            if tok.kind == "eof":
                raise SyntaxError("unterminated SQL statement in body")
            if tok.value == "(":
                depth += 1
            elif tok.value == ")":
                depth -= 1
            if tok.value == ";" and depth == 0:
                end = tok.pos
                self.next()
                break
            toks.append((tok, depth))
            self.next()
        text = self.sql[start:end]
        into: tuple[str, ...] = ()
        if toks and toks[0][0].value == "select":
            # SELECT ... INTO v[, v] [FROM ...]: strip the INTO clause at
            # the TOKEN level — a string literal containing ' into ', or
            # an INTO in a subquery (depth > 0), must not match.
            ii = next((k for k, (tk, d) in enumerate(toks)
                       if d == 0 and tk.kind == "kw" and tk.value == "into"),
                      None)
            if ii is not None:
                jj = next((k for k in range(ii + 1, len(toks))
                           if toks[k][1] == 0
                           and toks[k][0].kind == "kw"
                           and toks[k][0].value == "from"), None)
                stop = jj if jj is not None else len(toks)
                # variable names may lex as kw (row, key, date, ...);
                # only the separating commas are ops
                into = tuple(tk.value for tk, _ in toks[ii + 1:stop]
                             if tk.kind in ("name", "kw"))
                j = toks[jj][0].pos if jj is not None else end
                text = self.sql[start:toks[ii][0].pos] + " " + self.sql[j:end]
        from . import parser as P

        return PlSql(P.parse_statement(text), into)

    def _stmts_until(self, *enders) -> tuple:
        out: list = []
        while self.peek().value not in enders:
            out.append(self._pl_statement())
        return tuple(out)

    def expr_node(self) -> A.Node:
        """One scalar expression as raw AST (interpreted host-side)."""
        return self.expr()


def parse_procedure(text: str) -> PlProcedure:
    return PlParser(text).parse_procedure()


# -------------------------------------------------------- interpreter

class _Return(Exception):
    def __init__(self, value):
        self.value = value


MAX_PL_OPS = 1_000_000  # runaway-loop guard (cte_max_recursion analog)


@dataclass
class PlInterpreter:
    """Executes a procedure against a session-like object exposing
    .sql(text)->ResultSet and .db (for nested CALL lookup)."""

    session: object
    depth: int = 0
    ops: list = field(default_factory=lambda: [0])

    def call(self, proc: PlProcedure, args: list):
        if self.depth > 64:
            raise PlError("procedure call depth exceeded")
        env: dict[str, object] = {}
        if len(args) != len(proc.params):
            raise PlError(
                f"{proc.name} expects {len(proc.params)} args, "
                f"got {len(args)}"
            )
        for p, a in zip(proc.params, args):
            env[p.name] = a
        try:
            self._run_block(proc.body, env)
        except _Return as r:
            return r.value, env
        return None, env

    def _tick(self):
        self.ops[0] += 1
        if self.ops[0] > MAX_PL_OPS:
            raise PlError("procedure exceeded the statement budget")

    def _run_block(self, body, env):
        for st in body:
            self._tick()
            self._run_stmt(st, env)

    def _run_stmt(self, st, env):
        if isinstance(st, PlDeclare):
            env[st.name] = (
                self._eval(st.default, env) if st.default is not None
                else None
            )
            return
        if isinstance(st, PlSet):
            if st.name not in env:
                raise PlError(f"unknown variable {st.name}")
            env[st.name] = self._eval(st.expr, env)
            return
        if isinstance(st, PlIf):
            for cond, body in st.branches:
                if self._truthy(self._eval(cond, env)):
                    self._run_block(body, env)
                    return
            self._run_block(st.orelse, env)
            return
        if isinstance(st, PlWhile):
            while self._truthy(self._eval(st.cond, env)):
                self._tick()
                self._run_block(st.body, env)
            return
        if isinstance(st, PlReturn):
            raise _Return(
                self._eval(st.expr, env) if st.expr is not None else None
            )
        if isinstance(st, PlCall):
            vals = [self._eval(a, env) for a in st.args]
            ret, callee_env = self._call_by_name(st.name, vals)
            # OUT/INOUT writeback for simple variable arguments
            proc = self._lookup(st.name)
            for p, anode in zip(proc.params, st.args):
                if p.mode in ("out", "inout") and isinstance(anode, A.Name) \
                        and len(anode.parts) == 1:
                    env[anode.parts[0]] = callee_env[p.name]
            return
        if isinstance(st, PlSql):
            stmt = _substitute_vars(st.stmt, env)
            # cache key = the STORED node's identity: substituted
            # literals parameterize inside the plan cache, so every CALL
            # reuses one compiled plan per embedded statement
            rs = self.session.run_statement(
                stmt, cache_key=f"#pl:{id(st.stmt)}")
            if st.into:
                if rs.nrows < 1:
                    raise PlError("SELECT INTO returned no rows")
                row = rs.rows()[0]
                if len(st.into) != len(row):
                    raise PlError("SELECT INTO arity mismatch")
                for n, v in zip(st.into, row):
                    env[n] = v
            return
        raise PlError(f"unknown PL statement {type(st).__name__}")

    def _lookup(self, name) -> PlProcedure:
        proc = self.session.lookup_procedure(name)
        if proc is None:
            raise PlError(f"no procedure {name}")
        return proc

    def _call_by_name(self, name, vals):
        sub = PlInterpreter(self.session, self.depth + 1, self.ops)
        return sub.call(self._lookup(name), vals)

    @staticmethod
    def _truthy(v) -> bool:
        return bool(v) and v is not None

    def _eval(self, node, env):
        """Scalar expression evaluation over host values + variables."""
        self._tick()
        if isinstance(node, A.NumberLit):
            v = node.value
            try:
                return int(v)
            except ValueError:
                return float(v)  # '.' or scientific notation (1e5)
        if isinstance(node, A.StringLit):
            return node.value
        if isinstance(node, A.Name):
            key = node.parts[-1]
            if len(node.parts) == 1 and key in env:
                return env[key]
            raise PlError(f"unknown variable {'.'.join(node.parts)}")
        if isinstance(node, A.BinOp):
            op = node.op
            if op == "and":
                return self._truthy(self._eval(node.left, env)) and \
                    self._truthy(self._eval(node.right, env))
            if op == "or":
                return self._truthy(self._eval(node.left, env)) or \
                    self._truthy(self._eval(node.right, env))
            l = self._eval(node.left, env)
            r = self._eval(node.right, env)
            if l is None or r is None:
                return None
            if op == "+":
                return l + r
            if op == "-":
                return l - r
            if op == "*":
                return l * r
            if op == "/":
                return l / r
            if op == "%":
                return l % r
            if op == "=":
                return l == r
            if op in ("!=", "<>"):
                return l != r
            if op == "<":
                return l < r
            if op == "<=":
                return l <= r
            if op == ">":
                return l > r
            if op == ">=":
                return l >= r
            raise PlError(f"unsupported operator {op}")
        if isinstance(node, A.UnaryOp):
            v = self._eval(node.operand, env)
            if node.op == "-":
                return -v if v is not None else None
            return not self._truthy(v)
        raise PlError(
            f"unsupported expression {type(node).__name__} in PL context"
        )


def _substitute_vars(node, env):
    """Rewrite single-part Name nodes bound in `env` into Literals — the
    bridge from PL variables into embedded SQL (plans then parameterize
    on those literals like any client statement)."""
    import dataclasses

    if isinstance(node, A.Name) and len(node.parts) == 1 \
            and node.parts[0] in env:
        v = env[node.parts[0]]
        if v is None:
            return A.Name(("null",))
        if isinstance(v, str):
            return A.StringLit(v)
        if isinstance(v, bool):
            return A.NumberLit(str(int(v)))
        return A.NumberLit(repr(v))
    if dataclasses.is_dataclass(node) and not isinstance(node, type):
        changes = {}
        for f in dataclasses.fields(node):
            cur = getattr(node, f.name)
            new = _substitute_vars(cur, env)
            if new is not cur:
                changes[f.name] = new
        return dataclasses.replace(node, **changes) if changes else node
    if isinstance(node, tuple):
        items = tuple(_substitute_vars(x, env) for x in node)
        if any(a is not b for a, b in zip(items, node)):
            return items
        return node
    return node


