"""Resolver: AST -> logical plan over typed expression IR.

Reference surface: the resolver layer producing ObDMLStmt/ObSelectStmt with
ObRawExpr trees (src/sql/resolver, ob_raw_expr.h). Scoping model: every
table reference gets an alias; resolved columns are named "<alias>.<col>"
internally, unqualified names resolve by unique suffix match across visible
scopes. Aggregates are extracted from SELECT/HAVING/ORDER BY into an
Aggregate node (avg decomposes into sum/count at planning).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field

import numpy as np

from ..core.dtypes import DataType, Field, Schema
from ..expr import ir as E
from . import ast as A

_counter = itertools.count()


# ---- logical operators ----------------------------------------------------


class LogicalOp:
    __slots__ = ()


@dataclass
class Scan(LogicalOp):
    table: str
    alias: str
    schema: Schema  # qualified names alias.col
    pushed_filter: E.Expr | None = None
    needed: tuple[str, ...] | None = None  # projection pruning


@dataclass
class Filter(LogicalOp):
    child: LogicalOp
    pred: E.Expr


@dataclass
class Project(LogicalOp):
    child: LogicalOp
    exprs: tuple[tuple[str, E.Expr], ...]  # (output name, expr)


@dataclass
class JoinOp(LogicalOp):
    kind: str  # inner | left | semi | anti | cross
    left: LogicalOp
    right: LogicalOp
    left_keys: tuple[E.Expr, ...] = ()
    right_keys: tuple[E.Expr, ...] = ()
    residual: E.Expr | None = None


@dataclass
class Aggregate(LogicalOp):
    child: LogicalOp
    group_keys: tuple[tuple[str, E.Expr], ...]  # (name, expr)
    aggs: tuple[tuple[str, str, E.Expr | None, bool], ...]
    # (output name, op in sum/count/min/max, input expr, distinct)
    # ROLLUP/CUBE/GROUPING SETS: index tuples into group_keys; the
    # executor aggregates once per set and NULL-fills absent keys
    # (the reference's EXPAND operator, ob_phy_operator_type.h)
    grouping_sets: tuple[tuple[int, ...], ...] | None = None


@dataclass
class Sort(LogicalOp):
    child: LogicalOp
    keys: tuple[tuple[E.Expr, bool], ...]  # (expr, descending)


@dataclass
class Limit(LogicalOp):
    child: LogicalOp
    n: int
    offset: int = 0


@dataclass
class Distinct(LogicalOp):
    child: LogicalOp


@dataclass
class TopN(LogicalOp):
    """Fused ORDER BY + LIMIT (the reference's top-n sort with pushdown,
    sql/engine/sort/ob_pd_topn_sort_filter.h). On TPU this collapses the
    full-capacity payload permutation of a Sort into a k-row gather."""

    child: LogicalOp
    keys: tuple[tuple["E.Expr", bool], ...]  # (expr, descending)
    n: int
    offset: int = 0


@dataclass
class SetOp(LogicalOp):
    """UNION / INTERSECT / EXCEPT. Columns align by position; output field
    names come from the left side. Reference: src/sql/engine/set (hash
    union/intersect/except operators)."""

    kind: str  # union | intersect | except
    all: bool
    left: LogicalOp
    right: LogicalOp


@dataclass
class Window(LogicalOp):
    """Window functions over the child relation. Output = child columns +
    one column per window function; row set and order are unchanged.
    funcs: (name, fn, arg expr | None, partition key exprs,
    ((order expr, descending), ...), extra) where `extra` is the frame
    tuple (unit, lo, hi) for aggregates/first_value/last_value, (offset,
    default expr | None) for lag/lead, the bucket count for ntile, None
    otherwise. Reference: src/sql/engine/window_function
    (ObWindowFunctionVecOp)."""

    child: LogicalOp
    funcs: tuple[
        tuple[
            str, str, "E.Expr | None",
            tuple["E.Expr", ...],
            tuple[tuple["E.Expr", bool], ...],
            object,
        ],
        ...,
    ]


def output_schema(op: LogicalOp) -> Schema:
    """Schema of an operator's output (qualified names)."""
    if isinstance(op, Scan):
        if op.needed is None:
            return op.schema
        return Schema(tuple(f for f in op.schema.fields if f.name in op.needed))
    if isinstance(op, Filter):
        return output_schema(op.child)
    if isinstance(op, Project):
        from ..expr.compile import infer_type

        child_s = output_schema(op.child)
        return Schema(
            tuple(Field(n, infer_type(e, child_s)) for n, e in op.exprs)
        )
    if isinstance(op, JoinOp):
        ls, rs = output_schema(op.left), output_schema(op.right)
        if op.kind in ("semi", "anti"):
            return ls
        nullable_left = op.kind == "full"
        nullable_right = op.kind in ("left", "full")
        fields = [
            Field(f.name, f.dtype.with_nullable(f.dtype.nullable or nullable_left))
            for f in ls.fields
        ]
        for f in rs.fields:
            fields.append(
                Field(f.name, f.dtype.with_nullable(f.dtype.nullable or nullable_right))
            )
        return Schema(tuple(fields))
    if isinstance(op, Aggregate):
        from ..expr.compile import infer_type

        child_s = output_schema(op.child)
        fields = [Field(n, infer_type(e, child_s)) for n, e in op.group_keys]
        for name, fn, arg, _ in op.aggs:
            if fn == "count":
                fields.append(Field(name, DataType.int64()))
            else:
                t = infer_type(arg, child_s)
                if fn == "sum" and t.is_decimal:
                    t = DataType.decimal(18, t.scale)
                elif fn == "sum" and t.is_integer:
                    t = DataType.int64()
                fields.append(Field(name, t))
        return Schema(tuple(fields))
    if isinstance(op, (Sort, Limit, Distinct, TopN)):
        return output_schema(op.child)
    if isinstance(op, SetOp):
        return setop_schema(output_schema(op.left), output_schema(op.right))
    if isinstance(op, Window):
        child_s = output_schema(op.child)
        fields = list(child_s.fields)
        for name, fn, arg, _pk, _ok, _x in op.funcs:
            fields.append(Field(name, window_out_type(fn, arg, child_s)))
        return Schema(tuple(fields))
    raise AssertionError(type(op))


def window_out_type(fn: str, arg, child_s: Schema) -> DataType:
    """Result type of one window function (mirrors aggregate typing)."""
    from ..expr.compile import infer_type

    if fn in ("row_number", "rank", "dense_rank", "count", "ntile"):
        return DataType.int64()
    if fn == "avg":
        return DataType.float64()
    t = infer_type(arg, child_s)
    if fn == "sum" and t.is_decimal:
        t = DataType.decimal(18, t.scale)
    elif fn == "sum" and t.is_integer:
        t = DataType.int64()
    if fn in ("lag", "lead", "first_value", "last_value"):
        # outside-partition reads / empty frames produce NULL
        return t.with_nullable(True)
    # frames can be empty only for sum/min/max of all-NULL inputs; keep
    # nullability from the argument
    return t


def setop_schema(ls: Schema, rs: Schema) -> Schema:
    """Positionally-aligned common schema of a set operation (names from the
    left side, types promoted per column)."""
    if len(ls.fields) != len(rs.fields):
        raise ResolveError(
            f"set operation arity mismatch: {len(ls.fields)} vs {len(rs.fields)}"
        )
    fields = []
    for lf, rf in zip(ls.fields, rs.fields):
        fields.append(Field(lf.name, promote_types(lf.dtype, rf.dtype)))
    return Schema(tuple(fields))


def promote_types(l: DataType, r: DataType) -> DataType:
    """Common type of two set-operation branch columns."""
    from ..core.dtypes import common_numeric_type

    nullable = l.nullable or r.nullable
    if l.kind == r.kind:
        if l.is_decimal and (l.scale, l.precision) != (r.scale, r.precision):
            return DataType.decimal(18, max(l.scale, r.scale), nullable=nullable)
        return l.with_nullable(nullable)
    if l.is_numeric and r.is_numeric:
        return common_numeric_type(l, r).with_nullable(nullable)
    raise ResolveError(f"set operation type mismatch: {l} vs {r}")


# ---- resolver -------------------------------------------------------------

_AGG_FUNCS = {"sum", "count", "min", "max", "avg", "approx_count_distinct"}


class ResolveError(Exception):
    pass


@dataclass
class ResolvedQuery:
    plan: LogicalOp
    output_names: tuple[str, ...]


class Resolver:
    """One instance per (sub)query block."""

    def __init__(self, catalog, outer: "Resolver | None" = None):
        self.catalog = catalog  # dict name -> Table (core.table.Table)
        self.outer = outer
        self.scopes: list[tuple[str, Schema]] = []  # (alias, schema)
        # merged-view aliases (ob_transform_view_merge): view alias ->
        # {output column -> qualified inner column}; consulted by
        # resolve_name so outer references to the view splice straight
        # onto the inlined base tables
        self.redirects: dict[str, dict[str, str]] = {}
        self.agg_exprs: list[tuple[str, str, E.Expr | None, bool]] = []
        self.correlated: list[E.Expr] = []
        # window-function sink: (name, fn, arg, partition keys, order keys);
        # filled when WindowCall nodes resolve (planner builds the Window op)
        self.win_exprs: list[tuple] = []

    # -- name resolution -------------------------------------------------
    def add_table(self, name: str, alias: str) -> Scan:
        if name not in self.catalog:
            raise ResolveError(f"unknown table {name}")
        t = self.catalog[name]
        qual = Schema(
            tuple(Field(f"{alias}.{f.name}", f.dtype) for f in t.schema.fields)
        )
        self.scopes.append((alias, qual))
        return Scan(name, alias, qual)

    def resolve_name(self, parts: tuple[str, ...]) -> str:
        if len(parts) == 2:
            alias, col = parts
            rd = self.redirects.get(alias)
            if rd is not None:
                if col in rd:
                    return rd[col]
                raise ResolveError(f"unknown column {'.'.join(parts)}")
            for a, s in self.scopes:
                if a == alias:
                    q = f"{a}.{col}"
                    if q in s:
                        return q
            if self.outer is not None:
                return self.outer.resolve_name(parts)
            raise ResolveError(f"unknown column {'.'.join(parts)}")
        col = parts[0]
        matches = []
        for a, s in self.scopes:
            if "#" in a:
                # merged-view internals: reachable only through the view's
                # redirect map, never by bare-name search (columns outside
                # the view's select list stay hidden)
                continue
            q = f"{a}.{col}"
            if q in s:
                matches.append(q)
        for rd in self.redirects.values():
            if col in rd and rd[col] not in matches:
                matches.append(rd[col])
        if len(matches) == 1:
            return matches[0]
        if len(matches) > 1:
            raise ResolveError(f"ambiguous column {col}")
        if self.outer is not None:
            return self.outer.resolve_name(parts)
        raise ResolveError(f"unknown column {col}")

    def visible_schema(self) -> Schema:
        fields = []
        for _, s in self.scopes:
            fields.extend(s.fields)
        return Schema(tuple(fields))

    # -- expression resolution -------------------------------------------
    def expr(self, node: A.Node, allow_agg=False) -> E.Expr:
        if isinstance(node, A.Name):
            return E.ColRef(self.resolve_name(node.parts))
        if isinstance(node, A.NumberLit):
            if "." in node.value:
                return E.lit(float(node.value))
            return E.lit(int(node.value))
        if isinstance(node, A.StringLit):
            return E.lit(node.value)
        if isinstance(node, A.DateLit):
            days = int(np.datetime64(node.value, "D").astype(np.int64))
            return E.Literal(days, DataType.date())
        if isinstance(node, A.UnaryOp):
            if node.op == "-":
                inner = self.expr(node.operand, allow_agg)
                if isinstance(inner, E.Literal):
                    return E.Literal(-inner.value, inner.dtype)
                return E.Func("neg", (inner,))
            if self._contains_null_comparison(node.operand):
                # 3-valued logic: push the negation down (De Morgan) so
                # every NULL-comparison leaf folds in place — NOT(U OR p)
                # = (U AND NOT p) = false-in-WHERE, etc.
                return self._resolve_bool(node.operand, True, allow_agg)
            return E.Not(self.expr(node.operand, allow_agg))
        if isinstance(node, A.BinOp):
            return self._binop(node, allow_agg)
        if isinstance(node, A.BetweenOp):
            return E.Between(
                self.expr(node.expr, allow_agg),
                self.expr(node.low, allow_agg),
                self.expr(node.high, allow_agg),
                node.negated,
            )
        if isinstance(node, A.InOp):
            if node.subquery is not None:
                raise ResolveError("IN subquery handled by planner")
            vals = []
            for it in node.items:
                lit_e = self.expr(it, allow_agg)
                if not isinstance(lit_e, E.Literal):
                    raise ResolveError("IN list items must be literals")
                vals.append(lit_e.value)
            return E.InList(
                self.expr(node.expr, allow_agg), tuple(vals), node.negated
            )
        if isinstance(node, A.LikeOp):
            pat = self.expr(node.pattern)
            e = E.Func("like", (self.expr(node.expr, allow_agg), pat))
            return E.Not(e) if node.negated else e
        if isinstance(node, A.IsNullOp):
            return E.IsNull(self.expr(node.expr, allow_agg), node.negated)
        if isinstance(node, A.ExtractOp):
            return E.Func(
                f"extract_{node.field_}", (self.expr(node.expr, allow_agg),)
            )
        if isinstance(node, A.CaseOp):
            whens = tuple(
                (self.expr(c, allow_agg), self.expr(v, allow_agg))
                for c, v in node.whens
            )
            default = (
                self.expr(node.default, allow_agg)
                if node.default is not None
                else None
            )
            return E.Case(whens, default)
        if isinstance(node, A.CastOp):
            return E.Cast(self.expr(node.expr, allow_agg), _parse_type(node.type_name))
        if isinstance(node, A.SubstringOp):
            # substring(col from 1 for k) = 'lit'  -> handled as prefix in
            # comparisons; standalone substring resolves to a dict transform
            # at compile time (expr/compile handles Func('substr', ...)).
            start = self.expr(node.start)
            length = self.expr(node.length) if node.length else None
            if not (isinstance(start, E.Literal) and (length is None or isinstance(length, E.Literal))):
                raise ResolveError("substring bounds must be literals")
            return E.Func(
                "substr",
                (
                    self.expr(node.expr, allow_agg),
                    start,
                    length if length is not None else E.lit(-1),
                ),
            )
        if isinstance(node, A.WindowCall):
            return self._window_call(node, allow_agg)
        if isinstance(node, A.FuncCall):
            if node.name in _AGG_FUNCS:
                if not allow_agg:
                    raise ResolveError(f"aggregate {node.name} not allowed here")
                return self._agg_call(node)
            if node.name in ("vec_l2", "vec_ip", "vec_cosine"):
                return self._vec_l2_call(node, allow_agg)
            if node.name == "fts_match":
                # fts_match(varchar_col, 'tok tok ...') — word-level
                # full-text match; evaluation sweeps the column's
                # DICTIONARY (the engine's FTS 'index' is the dictionary
                # itself: one LUT per distinct value, not per row)
                from ..core.dtypes import TypeKind as _TK

                if len(node.args) != 2:
                    raise ResolveError("fts_match(column, 'tokens')")
                col = self.expr(node.args[0], allow_agg)
                ct = None
                if isinstance(col, E.ColRef):
                    for _alias, sc in self.scopes:
                        try:
                            ct = sc[col.name]
                            break
                        except Exception:
                            continue
                if ct is None or ct.kind is not _TK.VARCHAR:
                    raise ResolveError(
                        "fts_match first argument must be a VARCHAR column"
                    )
                q = self.expr(node.args[1], allow_agg)
                if not isinstance(q, E.Literal):
                    raise ResolveError("fts_match query must be a literal")
                return E.Func("fts_match", (col, q))
            if node.name in ("json_extract", "json_unquote", "json_valid",
                             "json_type", "json_array_length"):
                return self._json_call(node, allow_agg)
            if node.name in ("lower", "upper", "trim", "lcase", "ucase"):
                if len(node.args) != 1:
                    raise ResolveError(f"{node.name}(string)")
                canon = {"lcase": "lower", "ucase": "upper"}.get(
                    node.name, node.name)
                from ..expr.compile import CASE_FUNC_IMPL

                arg = self.expr(node.args[0], allow_agg)
                if isinstance(arg, E.Literal):
                    # constant fold (also the only executable form for a
                    # non-dictionary argument)
                    return E.lit(CASE_FUNC_IMPL[canon](str(arg.value)))
                return E.Func(canon, (arg,))
            if node.name in ("json_object", "json_array"):
                raise ResolveError(
                    f"{node.name} is supported in the select list only "
                    "(host-side construction, sql/json_host.py)")
            raise ResolveError(f"unknown function {node.name}")
        if isinstance(node, (A.ScalarSubquery, A.ExistsOp)):
            raise ResolveError("subquery handled by planner")
        if isinstance(node, A.IntervalLit):
            raise ResolveError("interval outside date arithmetic")
        raise ResolveError(f"cannot resolve {node!r}")

    def _json_call(self, node: A.FuncCall, allow_agg: bool) -> E.Expr:
        """JSON function family (ob_expr_json_extract.cpp and siblings):
        documents are dict-encoded varchar, so every function evaluates
        once per DISTINCT document through the expression compiler's
        string-view LUTs (expr/compile.py, expr/jsonpath.py)."""
        name = node.name
        if not node.args:
            raise ResolveError(f"{name} needs arguments")
        doc = self.expr(node.args[0], allow_agg)
        if name == "json_extract":
            if len(node.args) != 2:
                raise ResolveError("json_extract(doc, 'path')")
            p = self.expr(node.args[1], allow_agg)
            if not isinstance(p, E.Literal):
                raise ResolveError("json path must be a literal")
            self._check_json_path(str(p.value))
            return E.Func("json_extract", (doc, p))
        if name == "json_unquote":
            if len(node.args) != 1:
                raise ResolveError("json_unquote(value)")
            return E.Func("json_unquote", (doc,))
        if name == "json_valid":
            if len(node.args) != 1:
                raise ResolveError("json_valid(doc)")
            return E.Func("json_valid", (doc,))
        if name == "json_type":
            if len(node.args) == 2:
                p = self.expr(node.args[1], allow_agg)
                if not isinstance(p, E.Literal):
                    raise ResolveError("json path must be a literal")
                self._check_json_path(str(p.value))
                doc = E.Func("json_extract", (doc, p))
            return E.Func("json_type", (doc,))
        if name == "json_array_length":
            args = [doc]
            if len(node.args) == 2:
                p = self.expr(node.args[1], allow_agg)
                if not isinstance(p, E.Literal):
                    raise ResolveError("json path must be a literal")
                self._check_json_path(str(p.value))
                args.append(p)
            return E.Func("json_array_length", tuple(args))
        raise ResolveError(f"unknown function {name}")

    @staticmethod
    def _check_json_path(path: str) -> None:
        from ..expr.jsonpath import JsonPathError, parse_path

        try:
            parse_path(path)
        except JsonPathError as e:
            raise ResolveError(str(e)) from None

    @staticmethod
    def _is_null_comparison(node) -> bool:
        """A comparison with a bare NULL literal on either side."""
        def is_null_lit(n):
            return isinstance(n, A.Name) and n.parts == ("null",)

        return (
            isinstance(node, A.BinOp)
            and node.op in ("=", "!=", "<>", "<", "<=", ">", ">=")
            and (is_null_lit(node.left) or is_null_lit(node.right))
        )

    @classmethod
    def _contains_null_comparison(cls, node) -> bool:
        if cls._is_null_comparison(node):
            return True
        if isinstance(node, A.BinOp) and node.op in ("and", "or"):
            return (cls._contains_null_comparison(node.left)
                    or cls._contains_null_comparison(node.right))
        if isinstance(node, A.UnaryOp) and node.op != "-":
            return cls._contains_null_comparison(node.operand)
        return False

    _FALSE = None  # class-level constant-false built lazily

    def _resolve_bool(self, node, neg: bool, allow_agg) -> E.Expr:
        """Resolve a boolean skeleton with the negation pushed to the
        leaves, so NULL-comparison leaves fold to WHERE-false in any
        composition (a NULL result and FALSE are indistinguishable to a
        filter; the fold is only ever applied in predicate position)."""
        false_ = E.Compare("=", E.lit(0), E.lit(1))
        if self._is_null_comparison(node):
            return false_  # U and NOT U are both never-satisfied
        if isinstance(node, A.BinOp) and node.op in ("and", "or"):
            op = node.op if not neg else ("or" if node.op == "and" else "and")
            l = self._resolve_bool(node.left, neg, allow_agg)
            r = self._resolve_bool(node.right, neg, allow_agg)
            return E.and_(l, r) if op == "and" else E.or_(l, r)
        if isinstance(node, A.UnaryOp) and node.op != "-":
            return self._resolve_bool(node.operand, not neg, allow_agg)
        inner = self.expr(node, allow_agg)
        return E.Not(inner) if neg else inner

    def _binop(self, node: A.BinOp, allow_agg) -> E.Expr:
        op = node.op
        if op in ("and", "or"):
            l = self.expr(node.left, allow_agg)
            r = self.expr(node.right, allow_agg)
            return E.and_(l, r) if op == "and" else E.or_(l, r)
        if op in ("=", "!=", "<>", "<", "<=", ">", ">="):
            if self._is_null_comparison(node):
                # any comparison against NULL is SQL NULL: a typed NULL
                # literal keeps BOTH contexts honest — compile_predicate
                # rejects NULL rows in WHERE position, and a select-list
                # `(k = null) as b` projects NULL, not false
                return E.Literal(None, DataType.bool_(nullable=True))
            return E.Compare(
                op,
                self.expr(node.left, allow_agg),
                self.expr(node.right, allow_agg),
            )
        # date +- interval folding
        if op in ("+", "-") and isinstance(node.right, A.IntervalLit):
            base = self.expr(node.left, allow_agg)
            if isinstance(base, E.Literal) and base.dtype.kind.value == "date":
                days = _interval_shift(base.value, node.right, op)
                return E.Literal(days, DataType.date())
            raise ResolveError("interval arithmetic on non-literal date")
        return E.BinaryOp(
            op, self.expr(node.left, allow_agg), self.expr(node.right, allow_agg)
        )

    def _vec_l2_call(self, node: A.FuncCall, allow_agg) -> E.Expr:
        """vec_l2(vector_col, query): squared L2 distance. The query
        vector (a '[f, f, ...]' string literal) types as VECTOR(d) from
        the column so it can parameterize — one compiled plan serves
        every query vector (reference: obvec distance exprs over the
        vector index, src/storage/vector_index)."""
        if len(node.args) != 2:
            raise ResolveError(f"{node.name}(column, query_vector) takes 2 args")
        from ..core.dtypes import TypeKind

        col = self.expr(node.args[0], allow_agg)
        ct = None
        if isinstance(col, E.ColRef):
            for _alias, sc in self.scopes:
                try:
                    ct = sc[col.name]
                    break
                except Exception:
                    continue
        if ct is None or ct.kind is not TypeKind.VECTOR:
            raise ResolveError(
                f"{node.name} first argument must be a VECTOR column")
        q = self.expr(node.args[1], allow_agg)
        if not isinstance(q, E.Literal):
            raise ResolveError(
                f"{node.name} second argument must be a literal")
        return E.Func(node.name, (col, E.Literal(
            q.value, DataType(TypeKind.VECTOR, precision=ct.precision)
        )))

    def _agg_call(self, node: A.FuncCall) -> E.Expr:
        fn = node.name
        if fn == "approx_count_distinct":
            # the reference's NDV sketch (ob_expr_approx_count_distinct):
            # the executor runs a true fixed-memory HLL (ops/hll.py) on the
            # scalar path, and falls back to the exact first-occurrence
            # distinct count under GROUP BY (group cardinalities are
            # bounded by the group's row count there)
            if len(node.args) != 1:
                raise ResolveError(
                    "approx_count_distinct takes exactly one argument "
                    "(multi-column NDV is not supported)"
                )
            arg = self.expr(node.args[0])
            return E.ColRef(self._add_agg("approx_ndv", arg, False))
        if fn == "count" and (not node.args or isinstance(node.args[0], A.Star)):
            arg = None
        else:
            arg = self.expr(node.args[0])
        if fn == "avg":
            # avg(x) = sum(x) / count(x): count of NON-NULL x, per SQL;
            # AVG(DISTINCT x) needs BOTH halves deduplicated
            s = self._add_agg("sum", arg, node.distinct)
            c = self._add_agg("count", arg, node.distinct)
            return E.BinaryOp("/", E.ColRef(s), E.ColRef(c))
        name = self._add_agg(fn, arg, node.distinct)
        return E.ColRef(name)

    _WINDOW_FUNCS = {
        "row_number", "rank", "dense_rank", "sum", "count", "min", "max",
        "avg", "lag", "lead", "ntile", "first_value", "last_value",
    }
    # functions whose frame is fixed by the standard (frame clause invalid)
    _NO_FRAME = {"row_number", "rank", "dense_rank", "lag", "lead", "ntile"}

    def _window_call(self, node: "A.WindowCall", allow_agg: bool) -> E.Expr:
        """Resolve fn(args) OVER (...) to a ColRef on a window output column;
        the spec is recorded in win_exprs for the planner's Window node.
        avg decomposes into sum/count window functions (like _agg_call).

        The per-func `extra` slot carries the fn-specific spec: the frame
        tuple for aggregates/first_value/last_value; (offset, default expr)
        for lag/lead; the bucket count for ntile; None for ranking funcs.
        Reference: frame resolution in
        src/sql/engine/window_function/ob_window_function_vec_op.cpp."""
        fn = node.name
        if fn not in self._WINDOW_FUNCS:
            raise ResolveError(f"unknown window function {fn}")
        if node.frame is not None and fn in self._NO_FRAME:
            raise ResolveError(f"{fn}() does not accept a frame clause")
        frame = node.frame
        if frame is not None:
            if not node.order_by:
                raise ResolveError("a frame clause requires ORDER BY")
            unit, lo, hi = frame
            if lo is not None and hi is not None and lo > hi:
                raise ResolveError("frame start is after frame end")
            if unit == "range" and (lo not in (None, 0) or hi not in (None, 0)):
                if len(node.order_by) != 1:
                    raise ResolveError(
                        "RANGE frame with a value offset requires exactly "
                        "one ORDER BY key"
                    )
        extra = frame
        arg = None
        if fn in ("row_number", "rank", "dense_rank"):
            if node.args:
                raise ResolveError(f"{fn}() takes no arguments")
            extra = None
        elif fn == "ntile":
            if len(node.args) != 1 or not isinstance(node.args[0], A.NumberLit):
                raise ResolveError("ntile() takes one integer literal")
            try:
                k = int(node.args[0].value)
            except ValueError:
                raise ResolveError("ntile() bucket count must be an integer") \
                    from None
            if k <= 0:
                raise ResolveError("ntile() bucket count must be positive")
            extra = k
        elif fn in ("lag", "lead"):
            if not 1 <= len(node.args) <= 3:
                raise ResolveError(f"{fn}(expr [, offset [, default]])")
            arg = self.expr(node.args[0], allow_agg)
            off = 1
            if len(node.args) >= 2:
                if not isinstance(node.args[1], A.NumberLit):
                    raise ResolveError(f"{fn}() offset must be a literal")
                try:
                    off = int(node.args[1].value)
                except ValueError:
                    raise ResolveError(
                        f"{fn}() offset must be an integer") from None
                if off < 0:
                    raise ResolveError(f"{fn}() offset must be >= 0")
            dflt = (
                self.expr(node.args[2], allow_agg)
                if len(node.args) == 3 else None
            )
            extra = (off, dflt)
        elif fn == "count" and (
            not node.args or isinstance(node.args[0], A.Star)
        ):
            arg = None
        else:
            if len(node.args) != 1:
                raise ResolveError(f"window {fn} takes one argument")
            arg = self.expr(node.args[0], allow_agg)
        if fn in ("min", "max") and frame is not None:
            _u, lo, hi = frame
            if lo is not None and hi is not None:
                raise ResolveError(
                    "min/max windows support frames bounded on one end only"
                )
        if fn in ("rank", "dense_rank", "ntile", "lag", "lead") \
                and not node.order_by:
            raise ResolveError(f"{fn}() requires ORDER BY in its window")
        pk = tuple(self.expr(p, allow_agg) for p in node.partition_by)
        ok = tuple(
            (self.expr(oi.expr, allow_agg), oi.descending)
            for oi in node.order_by
        )
        if fn == "avg":
            s = self._add_window("sum", arg, pk, ok, extra)
            c = self._add_window("count", arg, pk, ok, extra)
            return E.BinaryOp("/", E.ColRef(s), E.ColRef(c))
        return E.ColRef(self._add_window(fn, arg, pk, ok, extra))

    def _add_window(self, fn, arg, pk, ok, extra=None) -> str:
        for name, f2, a2, p2, o2, x2 in self.win_exprs:
            if (f2, a2, p2, o2, x2) == (fn, arg, pk, ok, extra):
                return name
        name = f"$win{next(_counter)}"
        self.win_exprs.append((name, fn, arg, pk, ok, extra))
        return name

    def _add_agg(self, fn: str, arg: E.Expr | None, distinct: bool) -> str:
        # dedupe identical aggregates
        for name, f2, a2, d2 in self.agg_exprs:
            if f2 == fn and a2 == arg and d2 == distinct:
                return name
        name = f"$agg{next(_counter)}"
        self.agg_exprs.append((name, fn, arg, distinct))
        return name


def _interval_shift(days: int, iv: A.IntervalLit, op: str) -> int:
    n = int(iv.value)
    if op == "-":
        n = -n
    d = np.datetime64(int(days), "D")
    if iv.unit.startswith("day"):
        return int((d + np.timedelta64(n, "D")).astype(np.int64))
    if iv.unit.startswith("month") or iv.unit.startswith("year"):
        months = n if iv.unit.startswith("month") else 12 * n
        m = d.astype("datetime64[M]") + np.timedelta64(months, "M")
        dom = (d - d.astype("datetime64[M]")).astype(np.int64)
        # clamp to the target month's last day (SQL/MySQL semantics:
        # '1995-01-31' + 1 month = '1995-02-28', no overflow into March)
        next_m = (m + np.timedelta64(1, "M")).astype("datetime64[D]")
        last_dom = (next_m - m.astype("datetime64[D]")).astype(np.int64) - 1
        dom = min(int(dom), int(last_dom))
        return int((m.astype("datetime64[D]") + np.timedelta64(dom, "D")).astype(np.int64))
    raise ResolveError(f"interval unit {iv.unit}")


def _parse_type(tn: str) -> DataType:
    tn = tn.lower()
    if tn.endswith("?"):  # DataType.__str__ nullable marker round-trip
        return _parse_type(tn[:-1]).with_nullable(True)
    if tn in ("text", "mediumtext", "longtext", "blob", "clob", "json"):
        # LOB surface: dict-encoded varchar holds unbounded values (the
        # dictionary stores the full string ONCE; rows are int32 codes),
        # so TEXT/BLOB map onto the same storage. The reference's
        # out-of-row LOB store (src/storage/lob) exists because its rows
        # are materialized; columnar dict codes make that machinery moot
        # at this engine's scale.
        return DataType.varchar()
    if tn.startswith("vector"):
        if "(" not in tn:
            raise ResolveError("VECTOR needs a dimension: vector(d)")
        d = int(tn[tn.index("(") + 1:tn.index(")")])
        return DataType.vector(d)
    if tn.startswith("decimal") or tn.startswith("numeric"):
        if "(" in tn:
            inner = tn[tn.index("(") + 1 : tn.index(")")]
            p, *rest = inner.split(",")
            return DataType.decimal(int(p), int(rest[0]) if rest else 0)
        return DataType.decimal(18, 0)
    if "(" in tn:
        tn = tn[: tn.index("(")]  # varchar(25), char(1), int(11): length
        # modifiers don't change the physical type
    # accepts both SQL spellings and DataType.__str__ round-trip forms
    if tn in ("int", "integer", "smallint", "tinyint", "mediumint", "int32"):
        return DataType.int32()
    if tn in ("bigint", "int64"):
        return DataType.int64()
    if tn == "int8":
        return DataType.int8()
    if tn == "int16":
        return DataType.int16()
    if tn in ("float", "double", "real", "float64"):
        return DataType.float64()
    if tn == "float32":
        return DataType.float32()
    if tn == "bool":
        return DataType.bool_()
    if tn == "date":
        return DataType.date()
    if tn == "timestamp":
        return DataType.timestamp()
    if tn in ("varchar", "char", "text"):
        return DataType.varchar()
    raise ResolveError(f"unknown type {tn}")
