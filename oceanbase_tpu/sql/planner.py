"""Query planner: AST -> resolved, rewritten, join-ordered logical plan.

Reference surfaces:
- rewrite: the 82-rule transformer (src/sql/rewrite/ob_transformer_impl.h).
  Implemented rules: conjunct splitting, equi-join extraction, predicate
  pushdown to scans, OR-common-conjunct hoisting (or-expansion analog),
  subquery unnesting (ob_transform_subquery_coalesce/aggr_subquery):
    EXISTS / IN-subquery        -> semi / anti join with lifted correlation
    correlated scalar aggregate -> group-by over correlation keys + join
    uncorrelated scalar agg     -> 1-row aggregate broadcast-joined
  DISTINCT-aggregate expansion (distinct pre-dedup, the two-phase analog of
  the reference's distinct-agg hash infra).
- optimizer: CBO join ordering (src/sql/optimizer/ob_join_order.h) — greedy
  connected-subgraph heuristic on estimated filtered cardinalities.

Derived tables (FROM subqueries) and CTEs plan their block recursively and
join as relations whose outputs are renamed into the block's namespace.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, replace

from ..core.dtypes import Schema
from ..expr import ir as E
from . import ast as A
from .logical import (
    Aggregate,
    Distinct,
    Filter,
    JoinOp,
    Limit,
    LogicalOp,
    Project,
    ResolveError,
    Resolver,
    Scan,
    SetOp,
    Sort,
    TopN,
    Window,
    output_schema,
)

_sub_counter = itertools.count()


@dataclass
class PlannedQuery:
    plan: LogicalOp
    output_names: tuple[str, ...]


def capture_node_estimates(executor, plan: LogicalOp) -> dict:
    """Optimizer cardinality estimate per pre-order node id, keyed
    exactly like the compiled program's node numbering (the executor
    re-numbers the ROUTED plan at compile time, so callers pass that
    plan, not the raw planner output). Captured once at compile time and
    pinned to the PreparedPlan / plan artifact, so every profiled actual
    (engine/plan_profile.py) pairs with the estimate the optimizer
    planned with — not a later re-estimate over evolved stats."""
    from ..engine.executor import _number_nodes

    return {
        nid: int(executor._est_rows(op))
        for nid, op in _number_nodes(plan).items()
    }


@dataclass
class Relation:
    """One FROM item: a base scan or a planned derived table."""

    alias: str
    plan: LogicalOp
    is_scan: bool

    @property
    def scan(self) -> Scan:
        assert isinstance(self.plan, Scan)
        return self.plan


def split_conjuncts(e: E.Expr | None) -> list[E.Expr]:
    if e is None:
        return []
    if isinstance(e, E.BoolOp) and e.op == "and":
        out = []
        for a in e.args:
            out.extend(split_conjuncts(a))
        return out
    return [e]


def split_ast_conjuncts(node: A.Node | None) -> list[A.Node]:
    if node is None:
        return []
    if isinstance(node, A.BinOp) and node.op == "and":
        return split_ast_conjuncts(node.left) + split_ast_conjuncts(node.right)
    return [node]


def hoist_common_or_conjuncts(e: E.Expr) -> list[E.Expr]:
    """OR(a&b&c, a&d) -> [a, OR(b&c, d)] — factors conjuncts common to every
    OR branch so join keys and single-table filters buried in OR arms (TPC-H
    Q19 shape) become visible to pushdown/join extraction. (Reference: the
    or-expansion transform family, sql/rewrite/ob_transform_or_expansion.*.)
    """
    if not (isinstance(e, E.BoolOp) and e.op == "or"):
        return [e]
    branches = [split_conjuncts(b) for b in e.args]
    common = [c for c in branches[0] if all(c in b for b in branches[1:])]
    if not common:
        return [e]
    rest_branches = []
    for b in branches:
        rest = [c for c in b if c not in common]
        rest_branches.append(E.and_(*rest) if rest else E.lit(True))
    if any(isinstance(rb, E.Literal) for rb in rest_branches):
        return common
    return common + [E.or_(*rest_branches)]


def or_to_in(e: E.Expr) -> E.Expr:
    """OR of equalities on ONE column against literals -> InList
    (x=1 OR x=2 OR x=3 -> x IN (1,2,3)): one vectorized membership test
    instead of an OR chain, and a stabler plan-cache shape. (Reference:
    sql/rewrite or-expansion / in-list normalization.)"""
    if not (isinstance(e, E.BoolOp) and e.op == "or"):
        return e
    col = None
    vals = []
    for b in e.args:
        if not (
            isinstance(b, E.Compare) and b.op in ("=", "==")
            and isinstance(b.left, E.ColRef)
            and isinstance(b.right, E.Literal)
        ):
            return e
        if col is None:
            col = b.left.name
        elif b.left.name != col:
            return e
        vals.append(b.right.value)
    if col is None or len(vals) < 2:
        return e
    dtypes = {type(v) for v in vals}
    if len(dtypes) != 1:
        return e
    return E.InList(E.ColRef(col), tuple(vals))


def _tables_of(e: E.Expr) -> set[str]:
    return {n.split(".", 1)[0] for n in E.referenced_columns(e)}


def _is_equi_join(e: E.Expr) -> tuple[E.ColRef, E.ColRef] | None:
    if (
        isinstance(e, E.Compare)
        and e.op in ("=", "==")
        and isinstance(e.left, E.ColRef)
        and isinstance(e.right, E.ColRef)
    ):
        lt = e.left.name.split(".", 1)[0]
        rt = e.right.name.split(".", 1)[0]
        if lt != rt:
            return e.left, e.right
    return None


def _contains_subquery(node: A.Node) -> bool:
    if isinstance(node, (A.ScalarSubquery, A.ExistsOp)):
        return True
    if isinstance(node, A.InOp) and node.subquery is not None:
        return True
    for attr in getattr(node, "__dataclass_fields__", {}):
        v = getattr(node, attr)
        if isinstance(v, A.Node) and _contains_subquery(v):
            return True
        if isinstance(v, tuple):
            for x in v:
                if isinstance(x, A.Node) and _contains_subquery(x):
                    return True
                if isinstance(x, tuple) and any(
                    isinstance(y, A.Node) and _contains_subquery(y) for y in x
                ):
                    return True
    return False


class Planner:
    def __init__(self, catalog, stats=None, unique_keys=None, views=None):
        self.catalog = catalog  # name -> Table
        # share/stats.StatsManager (None = heuristic-only estimates)
        self.stats = stats
        # table -> unique key column tuple (DISTINCT elimination)
        self.unique_keys = unique_keys or {}
        self.ctes: dict[str, A.Select] = {}
        # plain views: name -> defining SELECT text (shared MUTABLE dict —
        # the server's DDL updates it in place). Expanded at plan time;
        # simple SPJ bodies MERGE into the referencing block
        # (ob_transform_view_merge), everything else plans as a derived
        # table. Plan-cache safety: planning precedes the cache lookup and
        # plan_fingerprint is part of the key, so redefinition changes the
        # key automatically.
        self.views: dict[str, str] = views if views is not None else {}
        self._view_depth = 0

    def _distinct_redundant(self, plan) -> bool:
        """True when `plan`'s rows are already unique, so a Distinct above
        it is a no-op (reference: ob_transform_distinct_elimination):
        a projection carrying ALL group keys of an Aggregate below it, or
        ALL unique-key columns of a single base table."""
        if not isinstance(plan, Project):
            return False
        srcs = {
            e.name for _n, e in plan.exprs if isinstance(e, E.ColRef)
        }
        node = plan.child
        if isinstance(node, Aggregate) and node.group_keys:
            return {n for n, _ in node.group_keys} <= srcs
        while isinstance(node, Filter):
            node = node.child
        if isinstance(node, Scan):
            uk = self.unique_keys.get(node.table)
            if uk:
                qual = {f"{node.alias}.{c}" for c in uk}
                return qual <= srcs
        return False

    # -- cardinality estimates (stats-backed with heuristic fallback) --
    def _scan_rows(self, scan: Scan) -> float:
        if scan.table == "$dual":
            return 1.0
        t = self.catalog[scan.table]
        base = t.nrows or 1
        if scan.pushed_filter is not None:
            ts = self.stats.table_stats(scan.table) if self.stats else None
            if ts is not None and ts.nrows > 0:
                base = base * ts.selectivity(scan.pushed_filter, t)
            else:
                n_conj = len(split_conjuncts(scan.pushed_filter))
                base = base * (0.25 ** min(n_conj, 3))
        return max(base, 1.0)

    def _rel_rows(self, rel: Relation) -> float:
        if rel.is_scan:
            return self._scan_rows(rel.scan)
        return self._est_op(rel.plan)

    def _est_op(self, op) -> float:
        if isinstance(op, Scan):
            return self._scan_rows(op)
        if isinstance(op, Filter):
            return max(self._est_op(op.child) * 0.5, 1.0)
        if isinstance(op, Aggregate):
            return max(self._est_op(op.child) * 0.1, 1.0)
        if isinstance(op, JoinOp):
            return max(self._est_op(op.left), self._est_op(op.right))
        if isinstance(op, (Project, Sort, Distinct)):
            return self._est_op(op.child)
        if isinstance(op, Limit):
            return float(op.n)
        return 1e4

    # ================================================================ API
    def plan(self, sel: "A.Select | A.SetSelect", outer: Resolver | None = None) -> PlannedQuery:
        for name, csel in getattr(sel, "ctes", ()):
            self.ctes[name] = csel
        if isinstance(sel, A.SetSelect):
            return self._plan_setop(sel, outer)
        plan, r, out_items, visible = self._plan_block(sel, outer)
        plan = self._simplify_outer_joins(plan)
        plan = self._eliminate_left_joins(plan)
        return PlannedQuery(plan, visible)

    def _simplify_outer_joins(self, op, null_rejected: frozenset = frozenset()):
        """Outer-join elimination (ob_transform_simplify's outer->inner
        rule): a LEFT join under a NULL-REJECTING predicate on its right
        side cannot produce surviving null-extended rows, so it is an
        inner join — which unlocks the engine's merge/affine fast paths
        and the right-deep rotation that left joins block.

        `null_rejected` carries columns that some ancestor filter
        rejects NULLs on (comparisons, BETWEEN, IN: all yield NULL/false
        for NULL inputs, and compile_predicate drops those rows)."""
        if isinstance(op, Filter):
            nr = set(null_rejected)
            for c in split_conjuncts(op.pred):
                nr |= _null_rejecting_cols(c)
            child = self._simplify_outer_joins(op.child, frozenset(nr))
            return op if child is op.child else replace(op, child=child)
        if isinstance(op, JoinOp):
            kind = op.kind
            if kind in ("left", "full"):
                rej_r = any(n in null_rejected
                            for n in output_schema(op.right).names())
                rej_l = kind == "full" and any(
                    n in null_rejected
                    for n in output_schema(op.left).names())
                if kind == "full":
                    if rej_l and rej_r:
                        kind = "inner"
                    elif rej_r:
                        kind = "left"
                    # rej_l alone would be a RIGHT join (the resolver
                    # mirrors those away; not representable here): keep
                elif rej_r:
                    kind = "inner"
            # predicates keep rejecting through the preserved (probe)
            # side; the null-extended sides reset
            left = self._simplify_outer_joins(
                op.left,
                null_rejected if kind in ("inner", "left", "semi", "anti")
                else frozenset(),
            )
            right = self._simplify_outer_joins(op.right, frozenset())
            if kind == op.kind and left is op.left and right is op.right:
                return op
            return replace(op, kind=kind, left=left, right=right)
        if isinstance(op, (Project, Sort, Distinct, Limit, TopN)):
            # only Sort/Distinct are sound pass-throughs: a Limit/TopN
            # below the filter SAMPLES rows, and converting a join under
            # it changes which rows the sample draws from; Project
            # renames would need mapping through
            passes = isinstance(op, (Sort, Distinct))
            child = self._simplify_outer_joins(
                op.child, null_rejected if passes else frozenset())
            return op if child is op.child else replace(op, child=child)
        if hasattr(op, "child"):
            child = self._simplify_outer_joins(op.child, frozenset())
            return op if child is op.child else replace(op, child=child)
        if isinstance(op, SetOp):
            left = self._simplify_outer_joins(op.left, frozenset())
            right = self._simplify_outer_joins(op.right, frozenset())
            if left is op.left and right is op.right:
                return op
            return replace(op, left=left, right=right)
        return op

    def _plan_setop(self, node: A.SetSelect, outer: Resolver | None) -> PlannedQuery:
        lq = self.plan(node.left, outer)
        rq = self.plan(node.right, outer)
        if len(lq.output_names) != len(rq.output_names):
            raise ResolveError(
                f"set operation arity mismatch: {len(lq.output_names)} vs "
                f"{len(rq.output_names)}"
            )
        # align the right side positionally onto the left side's names
        rplan = Project(
            rq.plan,
            tuple(
                (ln, E.ColRef(rn))
                for ln, rn in zip(lq.output_names, rq.output_names)
            ),
        )
        plan: LogicalOp = SetOp(node.kind, node.all, lq.plan, rplan)
        names = lq.output_names
        order_keys = []
        for oi in node.order_by:
            if (
                isinstance(oi.expr, A.Name)
                and len(oi.expr.parts) == 1
                and oi.expr.parts[0] in names
            ):
                order_keys.append((E.ColRef(oi.expr.parts[0]), oi.descending))
            elif isinstance(oi.expr, A.NumberLit):
                order_keys.append(
                    (E.ColRef(names[int(oi.expr.value) - 1]), oi.descending)
                )
            else:
                raise ResolveError(
                    "set-operation ORDER BY must use output names or ordinals"
                )
        if order_keys and node.limit is not None:
            plan = TopN(plan, tuple(order_keys), node.limit, node.offset or 0)
        elif order_keys:
            plan = Sort(plan, tuple(order_keys))
        elif node.limit is not None:
            plan = Limit(plan, node.limit, node.offset or 0)
        return PlannedQuery(plan, names)

    # ======================================================== block core
    def _plan_block(self, sel: A.Select, outer: Resolver | None):
        """Plan one SELECT block. Returns (plan, resolver, out_items, visible)."""
        r = Resolver({n: t for n, t in self.catalog.items()}, outer)

        relations: list[Relation] = []
        join_conds: list[E.Expr] = []
        outer_join_specs: list[tuple[str, str, A.Node | None]] = []  # (kind, right_alias, on)
        merged_where_asts: list[A.Node] = []
        outer_has_star = any(isinstance(it.expr, A.Star) for it in sel.items)

        def add_relation_from(node: A.Node, allow_merge: bool = True):
            if isinstance(node, A.TableRef):
                alias = node.alias or node.name
                if node.name in self.ctes:
                    relations.append(self._plan_derived(self.ctes[node.name], alias, r))
                elif node.name in self.views and node.name not in self.catalog:
                    if self._view_depth > 16:
                        raise ResolveError(
                            f"view expansion too deep at {node.name} "
                            "(cyclic views?)")
                    from .parser import parse as _parse

                    self._view_depth += 1
                    try:
                        body = _parse(self.views[node.name])
                        if (allow_merge
                                and not outer_has_star
                                and self._view_mergeable(body)):
                            # ob_transform_view_merge: splice the view's
                            # tables + predicates into THIS block so the
                            # optimizer join-orders across the boundary
                            # and predicates push into the view's scans
                            self._merge_view(
                                body, alias, r, add_relation_from,
                                merged_where_asts)
                        else:
                            relations.append(
                                self._plan_derived(body, alias, r))
                    finally:
                        self._view_depth -= 1
                else:
                    relations.append(Relation(alias, r.add_table(node.name, alias), True))
                return alias
            if isinstance(node, A.SubqueryRef):
                relations.append(self._plan_derived(node.subquery, node.alias, r))
                return node.alias
            if isinstance(node, A.Join):
                if node.kind == "inner" or node.kind == "cross":
                    add_relation_from(node.left)
                    add_relation_from(node.right)
                    if node.on is not None:
                        join_conds.extend(split_conjuncts(r.expr(node.on)))
                    return None
                if node.kind in ("left", "full"):
                    # the null-extended side must stay ONE relation — a
                    # merged view would splice in as inner tables and its
                    # WHERE would wrongly filter null-extended rows (FULL
                    # null-extends BOTH sides)
                    add_relation_from(
                        node.left, allow_merge=(node.kind == "left"))
                    ra = add_relation_from(node.right, allow_merge=False)
                    if ra is None:
                        raise ResolveError(
                            f"{node.kind} join right side must be a relation"
                        )
                    outer_join_specs.append((node.kind, ra, node.on))
                    return None
                if node.kind == "right":
                    # A RIGHT JOIN B == B LEFT JOIN A (the reference's
                    # resolver does the same side swap)
                    la = add_relation_from(node.right)
                    ra = add_relation_from(node.left, allow_merge=False)
                    if ra is None:
                        raise ResolveError("right join left side must be a relation")
                    outer_join_specs.append(("left", ra, node.on))
                    return None
                raise ResolveError(f"{node.kind} join not yet supported")
            raise ResolveError(f"bad FROM item {node!r}")

        for f in sel.from_:
            add_relation_from(f)

        # ---- WHERE: split AST conjuncts; subquery conjuncts unnest -----
        semi_specs = []  # (kind, sub_plan_rel, keys, residual)
        post_join_filters: list[E.Expr] = []
        where_conjs: list[E.Expr] = []
        where_ast_conjs = split_ast_conjuncts(sel.where)
        for mw in merged_where_asts:  # merged views' predicates (pushable)
            where_ast_conjs.extend(split_ast_conjuncts(mw))
        for ast_c in where_ast_conjs:
            if isinstance(ast_c, A.ExistsOp):
                semi_specs.append(self._plan_exists(ast_c.subquery, ast_c.negated, r))
            elif isinstance(ast_c, A.UnaryOp) and ast_c.op == "not" and isinstance(ast_c.operand, A.ExistsOp):
                semi_specs.append(
                    self._plan_exists(ast_c.operand.subquery, not ast_c.operand.negated, r)
                )
            elif isinstance(ast_c, A.InOp) and ast_c.subquery is not None:
                semi_specs.append(self._plan_in_subquery(ast_c, r))
            elif _contains_subquery(ast_c):
                rel, rewritten = self._plan_scalar_conjunct(ast_c, r)
                semi_specs.append(rel)
                post_join_filters.append(rewritten)
            else:
                where_conjs.extend(split_conjuncts(r.expr(ast_c)))

        where_conjs = join_conds + where_conjs
        where_conjs = [h for c in where_conjs for h in hoist_common_or_conjuncts(c)]
        where_conjs = [or_to_in(c) for c in where_conjs]

        # classify: single-relation -> pushdown; equi-join; residual
        by_alias = {rel.alias: rel for rel in relations}
        outer_right = {ra for _, ra, _ in outer_join_specs}

        # ---- predicate move-around (ob_transform_predicate_move_around):
        # x = y makes every single-column restriction on x equally true of
        # y, so the restriction CLONES onto y's relation and pre-filters
        # its scan — both scans shrink before the join instead of one
        where_conjs.extend(
            self._move_around_predicates(where_conjs, outer_right)
        )
        # a FULL join null-extends BOTH sides, so no WHERE conjunct may be
        # pushed below it — scans pre-filtered on the preserved side would
        # resurrect their partners as spurious unmatched rows
        has_full = any(kind == "full" for kind, _ra, _on in outer_join_specs)
        equi: list[tuple[E.ColRef, E.ColRef]] = []
        residual: list[E.Expr] = []
        post_outer: list[E.Expr] = []
        for c in where_conjs:
            tabs = _tables_of(c)
            ej = _is_equi_join(c)
            if (
                ej is not None
                and not has_full
                and not (
                    {ej[0].name.split(".")[0], ej[1].name.split(".")[0]}
                    & outer_right
                )
            ):
                equi.append(ej)
            elif (
                len(tabs) == 1
                and next(iter(tabs)) in by_alias
                and next(iter(tabs)) not in outer_right
                and not has_full
            ):
                rel = by_alias[next(iter(tabs))]
                self._push_filter(rel, c)
            elif (tabs & outer_right) or has_full:
                # references a null-extended side (or any side under a
                # FULL join): WHERE applies after the outer joins
                post_outer.append(c)
            else:
                residual.append(c)

        # ---- join order over inner relations; outer joins apply after --
        inner_rels = [rel for rel in relations if rel.alias not in outer_right]
        plan = self._order_joins(inner_rels, equi, residual)
        for kind, ra, on_ast in outer_join_specs:
            rel = by_alias[ra]
            on_conjs = split_conjuncts(r.expr(on_ast)) if on_ast is not None else []
            lkeys, rkeys, resid = [], [], []
            for c in on_conjs:
                ej = _is_equi_join(c)
                if ej is not None and (ra in (ej[0].name.split(".")[0], ej[1].name.split(".")[0])):
                    l_, r_ = ej
                    if l_.name.split(".")[0] == ra:
                        l_, r_ = r_, l_
                    lkeys.append(l_)
                    rkeys.append(r_)
                elif _tables_of(c) == {ra} and kind == "left":
                    # right-side-only ON condition filters the build input
                    # (LEFT join only: a FULL join must still emit right
                    # rows that fail the ON condition as unmatched)
                    self._push_filter(rel, c)
                else:
                    resid.append(c)
            plan = JoinOp(
                kind, plan, rel.plan, tuple(lkeys), tuple(rkeys),
                E.and_(*resid) if resid else None,
            )
        for c in post_outer:
            plan = Filter(plan, c)

        # ---- semi/anti/scalar joins on top of the join tree ------------
        for spec in semi_specs:
            kind, sub_plan, lkeys, rkeys, resid = spec
            plan = JoinOp(kind, plan, sub_plan, tuple(lkeys), tuple(rkeys), resid)
        for f in post_join_filters:
            plan = Filter(plan, f)

        # ---- GROUP BY / aggregates ------------------------------------
        alias_map: dict[str, E.Expr] = {}
        agg_out_sub: dict[E.Expr, E.Expr] = {}
        group_nodes = list(sel.group_by)
        has_agg_in_select = _select_has_agg(sel)
        agg_order_keys: list[tuple[E.Expr, bool]] | None = None
        scalar_join_after_agg: list[tuple] = []
        if group_nodes or has_agg_in_select or sel.having is not None:
            item_alias_ast = {
                it.alias: it.expr for it in sel.items if it.alias
            }
            key_exprs = []
            for i, g in enumerate(group_nodes):
                try:
                    ge = r.expr(g)
                except ResolveError:
                    # MySQL scoping: GROUP BY may name a select alias
                    if (isinstance(g, A.Name) and len(g.parts) == 1
                            and g.parts[0] in item_alias_ast):
                        ge = r.expr(item_alias_ast[g.parts[0]])
                        key_exprs.append((g.parts[0], ge))
                        continue
                    raise
                name = ge.name if isinstance(ge, E.ColRef) else f"$gkey{i}"
                key_exprs.append((name, ge))
            out_items = []
            for i, item in enumerate(sel.items):
                e = r.expr(item.expr, allow_agg=True)
                name = item.alias or _default_name(item.expr, i)
                out_items.append((name, e))
                alias_map[name] = e
            having_e = None
            if sel.having is not None:
                having_ast = sel.having
                if _contains_subquery(having_ast):
                    having_ast, scalar_join_after_agg = self._extract_having_subqueries(
                        having_ast, r
                    )
                having_e = r.expr(having_ast, allow_agg=True)
            agg_order_keys = []
            for oi in sel.order_by:
                if (
                    isinstance(oi.expr, A.Name)
                    and len(oi.expr.parts) == 1
                    and oi.expr.parts[0] in alias_map
                ):
                    agg_order_keys.append((E.ColRef(oi.expr.parts[0]), oi.descending))
                elif isinstance(oi.expr, A.NumberLit):
                    agg_order_keys.append(
                        (E.ColRef(out_items[int(oi.expr.value) - 1][0]), oi.descending)
                    )
                else:
                    oe = r.expr(oi.expr, allow_agg=True)
                    matched = [n for n, e2 in out_items if e2 == oe]
                    agg_order_keys.append(
                        (E.ColRef(matched[0]) if matched else oe, oi.descending)
                    )
            plan, agg_out_sub = self._build_aggregate(
                plan, key_exprs, r.agg_exprs,
                group_sets=getattr(sel, "group_sets", None),
            )
            out_items = [(n, _substitute(e, agg_out_sub)) for n, e in out_items]
            for kind, sub_plan, lkeys, rkeys, resid in scalar_join_after_agg:
                plan = JoinOp(kind, plan, sub_plan, tuple(lkeys), tuple(rkeys), resid)
            if having_e is not None:
                having_e = _substitute(having_e, agg_out_sub)
                plan = Filter(plan, having_e)
        else:
            out_items = []
            for i, item in enumerate(sel.items):
                if isinstance(item.expr, A.Star):
                    s = output_schema(plan)
                    for f in s.fields:
                        short = f.name.split(".", 1)[1] if "." in f.name else f.name
                        out_items.append((short, E.ColRef(f.name)))
                        alias_map[short] = E.ColRef(f.name)
                    continue
                e = r.expr(item.expr)
                name = item.alias or _default_name(item.expr, i)
                out_items.append((name, e))
                alias_map[name] = e

        # ---- ORDER BY (resolves select aliases, then input columns) ---
        if agg_order_keys is not None:
            order_keys = [
                (_substitute_out(e, out_items), d) for e, d in agg_order_keys
            ]
        else:
            order_keys = []
            for oi in sel.order_by:
                if (
                    isinstance(oi.expr, A.Name)
                    and len(oi.expr.parts) == 1
                    and oi.expr.parts[0] in alias_map
                ):
                    oe = E.ColRef(oi.expr.parts[0])
                elif isinstance(oi.expr, A.NumberLit):
                    oe = E.ColRef(out_items[int(oi.expr.value) - 1][0])
                else:
                    oe = r.expr(oi.expr)
                    matched = [n for n, e in out_items if e == oe]
                    oe = E.ColRef(matched[0]) if matched else oe
                order_keys.append((oe, oi.descending))

        # ---- window functions (after grouping/HAVING, before projection)
        if r.win_exprs:
            from ..expr.compile import infer_type
            from ..sql.logical import output_schema as _oschema

            specs = []
            for name, fn, arg, pk, ok, extra in r.win_exprs:
                if agg_out_sub:
                    arg = _substitute(arg, agg_out_sub) if arg is not None else None
                    pk = tuple(_substitute(p, agg_out_sub) for p in pk)
                    ok = tuple((_substitute(o, agg_out_sub), d) for o, d in ok)
                    if fn in ("lag", "lead") and extra is not None \
                            and extra[1] is not None:
                        extra = (extra[0], _substitute(extra[1], agg_out_sub))
                if (
                    isinstance(extra, tuple) and len(extra) == 3
                    and extra[0] == "range"
                    and (extra[1] not in (None, 0) or extra[2] not in (None, 0))
                ):
                    # value-offset RANGE frames run on the integer storage
                    # domain (ints, dates, scaled decimals); float keys
                    # would silently truncate
                    kt = infer_type(ok[0][0], _oschema(plan))
                    import numpy as _np

                    if not _np.issubdtype(kt.storage_np, _np.integer):
                        raise ResolveError(
                            "RANGE frame with a value offset requires an "
                            "integer-domain ORDER BY key (int/date/decimal)"
                        )
                specs.append((name, fn, arg, pk, ok, extra))
            plan = Window(plan, tuple(specs))

        visible = tuple(n for n, _ in out_items)
        fixed_order = []
        for i, (oe, d) in enumerate(order_keys):
            if isinstance(oe, E.ColRef) and any(n == oe.name for n, _ in out_items):
                fixed_order.append((oe, d))
            else:
                if sel.distinct:
                    raise ResolveError(
                        "ORDER BY expression must appear in the select list "
                        "of a SELECT DISTINCT"
                    )
                hidden = f"$ord{i}"
                out_items.append((hidden, oe))
                fixed_order.append((E.ColRef(hidden), d))
        order_keys = fixed_order

        plan = Project(plan, tuple(out_items))
        if sel.distinct and not self._distinct_redundant(plan):
            plan = Distinct(plan)
        if order_keys and sel.limit is not None:
            # ORDER BY + LIMIT fuse into top-n (ob_pd_topn_sort_filter
            # analog): only the surviving rows ever materialize
            plan = TopN(plan, tuple(order_keys), sel.limit, sel.offset or 0)
        elif order_keys:
            plan = Sort(plan, tuple(order_keys))
        elif sel.limit is not None:
            plan = Limit(plan, sel.limit, sel.offset or 0)

        return plan, r, out_items, visible

    # ------------------------------------------------- aggregate helper
    def _build_aggregate(self, plan, key_exprs, agg_exprs, group_sets=None):
        """Build the Aggregate node; expands DISTINCT aggregates into a
        pre-dedup (Distinct over keys+arg) + plain aggregate."""
        # group keys that are dictionary TRANSFORMS (substr / json_*)
        # cannot evaluate inside the aggregate (the engine's group-by
        # paths see plain columns): pre-project them below the Aggregate
        # into named dict columns (derive_dict_column) and group by those
        # select items referencing a transformed key must substitute by the
        # ORIGINAL expression, not the post-rewrite ColRef
        orig_key_exprs = list(key_exprs)
        from ..expr.compile import STRING_VIEW_FUNCS

        viewy = {
            n for n, e in key_exprs
            if isinstance(e, E.Func) and e.name in STRING_VIEW_FUNCS
        }
        if viewy:
            needed: set[str] = set()
            for _n, _fn, arg, _d in agg_exprs:
                if arg is not None:
                    needed |= set(E.referenced_columns(arg))
            for n, e in key_exprs:
                if n not in viewy:
                    needed |= set(E.referenced_columns(e))
            proj = [(n, e) for n, e in key_exprs if n in viewy]
            proj += [(c, E.ColRef(c)) for c in sorted(needed - viewy)]
            plan = Project(plan, tuple(proj))
            key_exprs = [
                (n, E.ColRef(n) if n in viewy else e) for n, e in key_exprs
            ]
        distinct_aggs = [a for a in agg_exprs if a[3]]
        if group_sets is not None:
            # ROLLUP/CUBE/GROUPING SETS: one EXPAND-style Aggregate
            # (executor replicates per set and NULL-masks missing keys)
            plan = Aggregate(plan, tuple(key_exprs), tuple(agg_exprs),
                             grouping_sets=tuple(group_sets))
            return plan, {e: E.ColRef(n) for n, e in key_exprs}
        if len(distinct_aggs) == 1 and len(agg_exprs) == 1 \
                and distinct_aggs[0][1] == "count":
            # lone COUNT(DISTINCT): pre-dedup (Distinct over keys+arg) +
            # plain count — two-phase, so under PX the dedup repartitions
            # before any aggregation state exists
            name, fn, arg, _ = distinct_aggs[0]
            proj = [(n, e) for n, e in key_exprs] + [("$darg", arg)]
            plan = Distinct(Project(plan, tuple(proj)))
            key_refs = [(n, E.ColRef(n)) for n, _ in key_exprs]
            plan = Aggregate(
                plan, tuple(key_refs),
                ((name, "count", E.ColRef("$darg"), False),),
            )
            sub = {e: E.ColRef(n) for n, e in orig_key_exprs}
            return plan, sub
        # mixed / multiple / non-count DISTINCT aggregates flow through:
        # the executor masks each distinct agg to first occurrences
        plan = Aggregate(plan, tuple(key_exprs), tuple(agg_exprs),
                         grouping_sets=group_sets)
        sub = {e: E.ColRef(n) for n, e in orig_key_exprs}
        return plan, sub

    # ------------------------------------------------- derived tables
    def _plan_derived(self, sub_sel: "A.Select | A.SetSelect", alias: str,
                      r: Resolver) -> Relation:
        if isinstance(sub_sel, A.SetSelect):
            pq = self._plan_setop(sub_sel, None)
            renamed = tuple(
                (f"{alias}.{n}", E.ColRef(n)) for n in pq.output_names
            )
            plan = Project(pq.plan, renamed)
            r.scopes.append((alias, output_schema(plan)))
            return Relation(alias, plan, False)
        sub_plan, _, out_items, visible = self._plan_block(sub_sel, None)
        # rename outputs into this block's namespace: alias.col
        renamed = tuple((f"{alias}.{n}", E.ColRef(n)) for n in visible)
        plan = Project(sub_plan, renamed)
        r.scopes.append((alias, output_schema(plan)))
        return Relation(alias, plan, False)

    # --------------------------------------------- predicate move-around
    @staticmethod
    def _move_around_predicates(where_conjs: list, outer_right: set) -> list:
        """Derive transferable restrictions across equi-join equivalence
        classes. Sound because an INNER equi-join result satisfies x = y
        with both non-NULL, so P(x) <=> P(y) on surviving rows; columns
        touching a null-extended side never participate."""
        eq_pairs = []
        for c in where_conjs:
            ej = _is_equi_join(c)
            if ej is None:
                continue
            if {ej[0].name.split(".")[0], ej[1].name.split(".")[0]} \
                    & outer_right:
                continue
            eq_pairs.append(ej)
        if not eq_pairs:
            return []
        parent: dict[str, str] = {}

        def find(x: str) -> str:
            while parent.get(x, x) != x:
                x = parent[x]
            return x

        for l_, r_ in eq_pairs:
            a, b = find(l_.name), find(r_.name)
            if a != b:
                parent[a] = b
        classes: dict[str, list[str]] = {}
        for n in sorted({n for p in eq_pairs for n in (p[0].name, p[1].name)}):
            classes.setdefault(find(n), []).append(n)
        seen = {repr(c) for c in where_conjs}
        derived = []
        for c in where_conjs:
            if _is_equi_join(c) is not None:
                continue
            refs = set(E.referenced_columns(c))
            if len(refs) != 1:
                continue
            (src,) = refs
            if src.split(".")[0] in outer_right:
                continue
            for other in classes.get(find(src), ()):
                if other == src or other.split(".")[0] in outer_right:
                    continue
                c2 = _substitute(c, {E.ColRef(src): E.ColRef(other)})
                if repr(c2) not in seen:
                    seen.add(repr(c2))
                    derived.append(c2)
        return derived

    # --------------------------------------------- join elimination
    @staticmethod
    def _node_col_refs(op) -> set:
        """Column names referenced by THIS node's expressions (children
        excluded)."""
        import dataclasses as _dc

        out: set = set()

        def grab(v):
            if isinstance(v, E.Expr):
                out.update(E.referenced_columns(v))
            elif isinstance(v, tuple):
                for x in v:
                    grab(x)

        for f in _dc.fields(op):
            v = getattr(op, f.name)
            if isinstance(v, LogicalOp):
                continue
            grab(v)
        return out

    def _eliminate_left_joins(self, op, needed: frozenset = frozenset()):
        """ob_transform_join_elimination: a LEFT JOIN on a UNIQUE key of
        the right side whose columns nothing above consumes changes
        neither row count (unique key -> at most one match per left row;
        unmatched rows null-extend) nor any surviving column — drop it."""
        import dataclasses as _dc

        if isinstance(op, JoinOp) and op.kind == "left":
            rnames = set(output_schema(op.right).names())
            if not (rnames & needed) and isinstance(op.right, Scan):
                uk = self.unique_keys.get(op.right.table)
                rk = {
                    k.name for k in op.right_keys if isinstance(k, E.ColRef)
                }
                if uk and {f"{op.right.alias}.{c}" for c in uk} == rk \
                        and len(rk) == len(op.right_keys):
                    return self._eliminate_left_joins(op.left, needed)
        # whole-row operators consume every child column implicitly
        if isinstance(op, (Distinct, SetOp)):
            sub_needed = needed
            for f in _dc.fields(op):
                v = getattr(op, f.name)
                if isinstance(v, LogicalOp):
                    sub_needed = sub_needed | set(output_schema(v).names())
        else:
            sub_needed = needed | frozenset(self._node_col_refs(op))
        kw = {}
        for f in _dc.fields(op):
            v = getattr(op, f.name)
            if isinstance(v, LogicalOp):
                v2 = self._eliminate_left_joins(v, frozenset(sub_needed))
                if v2 is not v:
                    kw[f.name] = v2
        return _dc.replace(op, **kw) if kw else op

    # ------------------------------------------------- view merge
    def _view_mergeable(self, body) -> bool:
        """True when the view body is simple select-project-join over
        catalog base tables: bare-column outputs, optional WHERE without
        subqueries, inner joins only (ob_transform_view_merge scope)."""
        if not isinstance(body, A.Select):
            return False
        if (body.group_by or body.having is not None or body.distinct
                or body.order_by or body.limit is not None or body.offset
                or body.ctes or body.group_sets or not body.from_):
            return False
        if _select_has_agg(body):
            return False
        if not all(isinstance(it.expr, A.Name) for it in body.items):
            return False
        if body.where is not None and _contains_subquery(body.where):
            return False

        def leafs_ok(node) -> bool:
            if isinstance(node, A.TableRef):
                return node.name in self.catalog
            if isinstance(node, A.Join):
                return (node.kind in ("inner", "cross")
                        and leafs_ok(node.left) and leafs_ok(node.right))
            return False

        return all(leafs_ok(f) for f in body.from_)

    def _merge_view(self, body: A.Select, alias: str, r,
                    add_relation_from, merged_where_asts: list) -> None:
        """Inline a mergeable view body into the CURRENT block: base
        tables join the outer relation list under gensym'd aliases, the
        view's WHERE joins the outer conjunct pool, and the view alias
        becomes a resolver REDIRECT mapping its output columns onto the
        inlined tables."""
        # inner alias -> (renamed alias, table name)
        ren: dict[str, tuple[str, str]] = {}

        def collect(node):
            if isinstance(node, A.TableRef):
                ia = node.alias or node.name
                # '#' is outside the lexer's name charset: the internal
                # alias is UNTYPEABLE, so user text can never address the
                # merged-in tables directly (a view grant must not leak
                # base columns outside the view's select list)
                ren[ia] = (f"{alias}#{ia}", node.name)
            else:
                collect(node.left)
                collect(node.right)

        for f in body.from_:
            collect(f)

        def owner_of(col: str) -> str:
            hits = [
                ra for ia, (ra, tn) in ren.items()
                if any(f.name == col for f in self.catalog[tn].schema.fields)
            ]
            if len(hits) != 1:
                raise ResolveError(
                    f"column {col} is {'ambiguous' if hits else 'unknown'} "
                    f"inside view {alias}")
            return hits[0]

        def rn_expr(node):
            """Requalify every column reference onto the renamed aliases
            (one shared walker: ast.rewrite)."""

            def fn(n):
                if not isinstance(n, A.Name):
                    return None
                if n.parts == ("null",):
                    return n
                if len(n.parts) == 2 and n.parts[0] in ren:
                    return A.Name((ren[n.parts[0]][0], n.parts[1]))
                if len(n.parts) == 1:
                    return A.Name((owner_of(n.parts[0]), n.parts[0]))
                return n

            return A.rewrite(node, fn)

        def rn_from(node):
            if isinstance(node, A.TableRef):
                ia = node.alias or node.name
                return A.TableRef(node.name, ren[ia][0])
            return A.Join(
                node.kind, rn_from(node.left), rn_from(node.right),
                rn_expr(node.on) if node.on is not None else None,
            )

        for f in body.from_:
            add_relation_from(rn_from(f))
        if body.where is not None:
            merged_where_asts.append(rn_expr(body.where))
        colmap: dict[str, str] = {}
        for it in body.items:
            parts = it.expr.parts
            if len(parts) == 2:
                tgt = f"{ren[parts[0]][0]}.{parts[1]}"
            else:
                tgt = f"{owner_of(parts[0])}.{parts[0]}"
            colmap[it.alias or parts[-1]] = tgt
        r.redirects[alias] = colmap

    def _push_filter(self, rel: Relation, c: E.Expr) -> None:
        if rel.is_scan:
            s = rel.scan
            s.pushed_filter = c if s.pushed_filter is None else E.and_(s.pushed_filter, c)
        else:
            rel.plan = Filter(rel.plan, c)

    # --------------------------------------------- subquery unnesting
    def _assemble_sub_block(self, sub_sel, sub, relations, join_conds,
                            where_conjs, correlated, local_aliases):
        by_alias = {rel.alias: rel for rel in relations}
        equi, residual = [], []
        for c in join_conds + where_conjs:
            for h in hoist_common_or_conjuncts(c):
                tabs = _tables_of(h)
                ej = _is_equi_join(h)
                if ej is not None and tabs <= local_aliases:
                    equi.append(ej)
                elif len(tabs) == 1 and next(iter(tabs)) in by_alias:
                    self._push_filter(by_alias[next(iter(tabs))], h)
                elif tabs <= local_aliases:
                    residual.append(h)
                else:
                    correlated.append(h)
        plan = self._order_joins(relations, equi, residual)
        return plan, sub, correlated

    def _split_correlation(self, correlated, local_aliases):
        """Split correlated conjuncts into equi key pairs (outer_col,
        inner_col) and residual correlated conditions."""
        keys, resid = [], []
        for c in correlated:
            ej = None
            if isinstance(c, E.Compare) and c.op in ("=", "=="):
                if isinstance(c.left, E.ColRef) and isinstance(c.right, E.ColRef):
                    lt = c.left.name.split(".")[0]
                    rt = c.right.name.split(".")[0]
                    if lt in local_aliases and rt not in local_aliases:
                        ej = (c.right, c.left)  # (outer, inner)
                    elif rt in local_aliases and lt not in local_aliases:
                        ej = (c.left, c.right)
            if ej is not None:
                keys.append(ej)
            else:
                resid.append(c)
        return keys, resid

    def _plan_exists(self, sub_sel: A.Select, negated: bool, r: Resolver):
        """EXISTS/NOT EXISTS -> semi/anti join spec."""
        plan, sub, correlated = self._plan_sub_block_simple(sub_sel, r)
        local_aliases = {a for a, _ in sub.scopes}
        keys, resid = self._split_correlation(correlated, local_aliases)
        if not keys:
            raise ResolveError("EXISTS without equi correlation is unsupported")
        sid = f"$sub{next(_sub_counter)}"
        # project inner columns referenced by keys/residual under new names
        inner_cols: dict[str, str] = {}
        proj = []
        rkeys = []
        for i, (oc, ic) in enumerate(keys):
            nn = f"{sid}.k{i}"
            inner_cols[ic.name] = nn
            proj.append((nn, ic))
            rkeys.append(E.ColRef(nn))
        resid2 = []
        for c in resid:
            for col in E.referenced_columns(c):
                if col.split(".")[0] in local_aliases and col not in inner_cols:
                    nn = f"{sid}.r{len(inner_cols)}"
                    inner_cols[col] = nn
                    proj.append((nn, E.ColRef(col)))
            resid2.append(_rename_cols(c, inner_cols))
        sub_plan = Project(plan, tuple(proj))
        kind = "anti" if negated else "semi"
        lkeys = [oc for oc, _ in keys]
        return (kind, sub_plan, lkeys, rkeys, E.and_(*resid2) if resid2 else None)

    def _plan_in_subquery(self, node: A.InOp, r: Resolver):
        """expr IN (SELECT item FROM ...) -> semi/anti join on equality."""
        outer_e = r.expr(node.expr)
        plan, sub, correlated = self._plan_sub_block_simple(node.subquery, r)
        local_aliases = {a for a, _ in sub.scopes}
        keys, resid = self._split_correlation(correlated, local_aliases)
        if len(node.subquery.items) != 1:
            raise ResolveError("IN subquery must select exactly one column")
        # resolve the selected item in the sub scope (may itself be grouped)
        plan_out, item_ref = self._sub_output_expr(node.subquery, plan, sub)
        sid = f"$sub{next(_sub_counter)}"
        proj = [(f"{sid}.v", item_ref)]
        rkeys = [E.ColRef(f"{sid}.v")]
        lkeys = [outer_e]
        inner_cols = {}
        for i, (oc, ic) in enumerate(keys):
            nn = f"{sid}.k{i+1}"
            inner_cols[ic.name] = nn
            proj.append((nn, ic))
            rkeys.append(E.ColRef(nn))
            lkeys.append(oc)
        resid2 = [_rename_cols(c, inner_cols) for c in resid]
        sub_plan = Project(plan_out, tuple(proj))
        kind = "anti" if node.negated else "semi"
        return (kind, sub_plan, lkeys, rkeys, E.and_(*resid2) if resid2 else None)

    def _sub_output_expr(self, sub_sel: A.Select, plan, sub: Resolver):
        """Resolve the single select item of an IN subquery over its plan.
        Handles grouped subqueries (Q18: group by + having) by planning the
        aggregate inside."""
        item = sub_sel.items[0]
        if sub_sel.group_by or _select_has_agg(sub_sel) or sub_sel.having is not None:
            key_exprs = []
            for i, g in enumerate(sub_sel.group_by):
                ge = sub.expr(g)
                name = ge.name if isinstance(ge, E.ColRef) else f"$gkey{i}"
                key_exprs.append((name, ge))
            e = sub.expr(item.expr, allow_agg=True)
            having_e = (
                sub.expr(sub_sel.having, allow_agg=True)
                if sub_sel.having is not None
                else None
            )
            plan, agg_sub = self._build_aggregate(plan, key_exprs, sub.agg_exprs)
            e = _substitute(e, agg_sub)
            if having_e is not None:
                plan = Filter(plan, _substitute(having_e, agg_sub))
            return plan, e
        return plan, sub.expr(item.expr)

    def _plan_sub_block_simple(self, sub_sel: A.Select, r: Resolver):
        """Plan a correlated sub block's FROM+WHERE (no select processing).
        Nested subqueries inside its WHERE unnest recursively."""
        sub = Resolver({n: t for n, t in self.catalog.items()}, outer=r)
        relations: list[Relation] = []
        join_conds: list[E.Expr] = []

        def add_from(node):
            if isinstance(node, A.TableRef):
                alias = node.alias or node.name
                if node.name in self.ctes:
                    relations.append(self._plan_derived(self.ctes[node.name], alias, sub))
                else:
                    relations.append(Relation(alias, sub.add_table(node.name, alias), True))
            elif isinstance(node, A.Join) and node.kind in ("inner", "cross"):
                add_from(node.left)
                add_from(node.right)
                if node.on is not None:
                    join_conds.extend(split_conjuncts(sub.expr(node.on)))
            else:
                raise ResolveError("unsupported FROM in correlated subquery")

        for f in sub_sel.from_:
            add_from(f)
        local_aliases = {rel.alias for rel in relations}

        nested_specs = []
        nested_filters = []
        correlated: list[E.Expr] = []
        where_conjs: list[E.Expr] = []
        for ast_c in split_ast_conjuncts(sub_sel.where):
            if isinstance(ast_c, A.ExistsOp):
                nested_specs.append(self._plan_exists(ast_c.subquery, ast_c.negated, sub))
            elif isinstance(ast_c, A.InOp) and ast_c.subquery is not None:
                nested_specs.append(self._plan_in_subquery(ast_c, sub))
            elif _contains_subquery(ast_c):
                spec, rewritten = self._plan_scalar_conjunct(ast_c, sub)
                nested_specs.append(spec)
                nested_filters.append(rewritten)
            else:
                c = sub.expr(ast_c)
                if _tables_of(c) <= local_aliases:
                    where_conjs.append(c)
                else:
                    correlated.append(c)

        plan, sub, correlated2 = self._assemble_sub_block(
            sub_sel, sub, relations, join_conds, where_conjs, correlated, local_aliases
        )
        for spec in nested_specs:
            kind, sp, lk, rk, resid = spec
            plan = JoinOp(kind, plan, sp, tuple(lk), tuple(rk), resid)
        for f in nested_filters:
            plan = Filter(plan, f)
        return plan, sub, correlated2

    def _plan_scalar_conjunct(self, ast_c: A.Node, r: Resolver):
        """A WHERE conjunct containing a scalar subquery: plan the subquery
        as a joinable relation and rewrite the conjunct over its output.

        Returns (join spec, rewritten conjunct expr). Inner-join semantics:
        an empty subquery result yields NULL, which fails any comparison, so
        dropping unmatched outer rows is equivalent for comparison conjuncts.
        """
        subs: list[A.ScalarSubquery] = []

        def find(n):
            if isinstance(n, A.ScalarSubquery):
                subs.append(n)
                return
            for attr in getattr(n, "__dataclass_fields__", {}):
                v = getattr(n, attr)
                if isinstance(v, A.Node):
                    find(v)
                elif isinstance(v, tuple):
                    for x in v:
                        if isinstance(x, A.Node):
                            find(x)

        find(ast_c)
        if len(subs) != 1:
            raise ResolveError("exactly one scalar subquery per conjunct supported")
        sub_sel = subs[0].subquery
        spec, value_name = self._plan_scalar_subquery(sub_sel, r)

        # rewrite the AST conjunct replacing the subquery with a column ref
        def rewrite(n):
            if isinstance(n, A.ScalarSubquery):
                return A.Name((value_name.split(".")[0], value_name.split(".")[1]))
            if not isinstance(n, A.Node):
                return n
            kwargs = {}
            for attr in getattr(n, "__dataclass_fields__", {}):
                v = getattr(n, attr)
                if isinstance(v, A.Node):
                    kwargs[attr] = rewrite(v)
                elif isinstance(v, tuple):
                    kwargs[attr] = tuple(
                        rewrite(x) if isinstance(x, A.Node) else x for x in v
                    )
                else:
                    kwargs[attr] = v
            return type(n)(**kwargs)

        rewritten_ast = rewrite(ast_c)
        rewritten = r.expr(rewritten_ast)
        return spec, rewritten

    def _plan_scalar_subquery(self, sub_sel: A.Select, r: Resolver):
        """Scalar aggregate subquery -> join spec.

        Uncorrelated: 1-row scalar Aggregate broadcast-joined (no keys).
        Correlated (equi): Aggregate grouped by correlation keys, inner join.
        """
        plan, sub, correlated = self._plan_sub_block_simple(sub_sel, r)
        local_aliases = {a for a, _ in sub.scopes}
        keys, resid = self._split_correlation(correlated, local_aliases)
        if resid:
            raise ResolveError("non-equi correlation in scalar subquery")
        if len(sub_sel.items) != 1:
            raise ResolveError("scalar subquery must select one expression")
        if not _select_has_agg(sub_sel) or sub_sel.group_by:
            raise ResolveError("scalar subquery must be a single aggregate")
        sid = f"$sub{next(_sub_counter)}"
        value_expr = sub.expr(sub_sel.items[0].expr, allow_agg=True)
        if keys:
            key_exprs = [(f"{sid}.k{i}", ic) for i, (_, ic) in enumerate(keys)]
            plan = Aggregate(plan, tuple(key_exprs), tuple(sub.agg_exprs))
            proj = [(n, E.ColRef(n)) for n, _ in key_exprs]
            proj.append((f"{sid}.v", value_expr))
            plan = Project(plan, tuple(proj))
            lkeys = [oc for oc, _ in keys]
            rkeys = [E.ColRef(n) for n, _ in key_exprs]
            # the sub's output joins the outer block: make it resolvable
            r.scopes.append((sid, output_schema(plan)))
            return ("inner", plan, lkeys, rkeys, None), f"{sid}.v"
        plan = Aggregate(plan, (), tuple(sub.agg_exprs))
        plan = Project(plan, ((f"{sid}.v", value_expr),))
        r.scopes.append((sid, output_schema(plan)))
        # broadcast: no keys; executor routes through the 1-row build path
        return ("inner", plan, [], [], None), f"{sid}.v"

    def _extract_having_subqueries(self, having_ast: A.Node, r: Resolver):
        """HAVING with scalar subqueries: plan each as a broadcast join to
        apply above the Aggregate; returns (rewritten AST, join specs)."""
        specs = []

        def rewrite(n):
            if isinstance(n, A.ScalarSubquery):
                spec, value_name = self._plan_scalar_subquery(n.subquery, r)
                specs.append(spec)
                a, b = value_name.split(".")
                return A.Name((a, b))
            if not isinstance(n, A.Node):
                return n
            kwargs = {}
            for attr in getattr(n, "__dataclass_fields__", {}):
                v = getattr(n, attr)
                if isinstance(v, A.Node):
                    kwargs[attr] = rewrite(v)
                elif isinstance(v, tuple):
                    kwargs[attr] = tuple(
                        rewrite(x) if isinstance(x, A.Node) else x for x in v
                    )
                else:
                    kwargs[attr] = v
            return type(n)(**kwargs)

        return rewrite(having_ast), specs

    # -------------------------------------------------------- join order
    def _order_joins(
        self,
        relations: list[Relation],
        equi: list[tuple[E.ColRef, E.ColRef]],
        residual: list[E.Expr],
    ) -> LogicalOp:
        if not relations:
            # FROM-less SELECT: a one-row dual relation (MySQL's implicit
            # DUAL); the executor serves '$dual' without a catalog entry
            from ..core.dtypes import DataType, Field as F, Schema as S

            plan = Scan(
                "$dual", "$dual",
                S((F("$dual.$one", DataType.int8()),)),
            )
            for c in residual:
                plan = Filter(plan, c)
            return plan
        if len(relations) == 1:
            plan = relations[0].plan
            for c in residual:
                plan = Filter(plan, c)
            return plan
        remaining = {rel.alias: rel for rel in relations}
        sizes = {rel.alias: self._rel_rows(rel) for rel in relations}
        alias_table = {
            rel.alias: (rel.scan.table if rel.is_scan else None)
            for rel in relations
        }

        def key_ndv(ref: E.ColRef) -> float | None:
            alias, col = ref.name.split(".", 1)
            t = alias_table.get(alias)
            if t is None or self.stats is None:
                return None
            ts = self.stats.table_stats(t)
            if ts is not None:
                n = ts.ndv_of(col)
                if n:
                    return float(n)
            uk = self.unique_keys.get(t)
            if uk and tuple(uk) == (col,):
                return float(self.catalog[t].nrows or 1)
            return None

        def est_out(cur: float, alias: str, keys) -> float:
            """|R join S| ~= |R||S| / max(V(R,k), V(S,k)) — the NDV rule
            that keeps many-to-many keys (Q5's c_nationkey=s_nationkey,
            25 distinct values over millions of rows) from being picked
            just because S itself is small."""
            rows_a = sizes[alias]
            best_sel = None
            for l, r_ in keys:
                a_ref, j_ref = (
                    (l, r_) if l.name.split(".")[0] == alias else (r_, l)
                )
                va = key_ndv(a_ref)
                vj = key_ndv(j_ref)
                denom = max(
                    min(va if va is not None else rows_a, rows_a),
                    min(vj if vj is not None else cur, cur),
                    1.0,
                )
                sel = 1.0 / denom
                best_sel = sel if best_sel is None else min(best_sel, sel)
            return cur * rows_a * (best_sel if best_sel is not None else 1.0)

        start = max(sizes, key=lambda a: sizes[a])
        joined = {start}
        plan = remaining.pop(start).plan
        cur_rows = sizes[start]
        pending_equi = list(equi)
        while remaining:
            best = None
            best_rank = None
            for alias in sorted(remaining):
                keys = [
                    (l, r_)
                    for l, r_ in pending_equi
                    if (
                        l.name.split(".")[0] in joined
                        and r_.name.split(".")[0] == alias
                    )
                    or (
                        r_.name.split(".")[0] in joined
                        and l.name.split(".")[0] == alias
                    )
                ]
                if not keys:
                    continue
                rank = (est_out(cur_rows, alias, keys), sizes[alias])
                if best_rank is None or rank < best_rank:
                    best = (alias, keys)
                    best_rank = rank
            if best is None:
                alias = min(remaining, key=lambda a: sizes[a])
                cur_rows *= max(sizes[alias], 1.0)
                plan = JoinOp("cross", plan, remaining.pop(alias).plan)
                joined.add(alias)
                continue
            alias, keys = best
            cur_rows = max(best_rank[0], 1.0)
            lkeys, rkeys = [], []
            for l, r_ in keys:
                if l.name.split(".")[0] == alias:
                    l, r_ = r_, l
                lkeys.append(l)
                rkeys.append(r_)
                pending_equi.remove(
                    (l, r_) if (l, r_) in pending_equi else (r_, l)
                )
            plan = JoinOp(
                "inner",
                plan,
                remaining.pop(alias).plan,
                tuple(lkeys),
                tuple(rkeys),
            )
            joined.add(alias)
        plan = self._rotate_right_deep(plan)
        leftover = [E.Compare("=", l, r_) for l, r_ in pending_equi] + residual
        for c in leftover:
            plan = Filter(plan, c)
        return plan

    def _rotate_right_deep(self, op) -> LogicalOp:
        """Rotate J2(J1(A, B), C) into J1(A, J2'(B, C)) when J2's join
        condition only touches B — join associativity, applied whenever A
        is the bigger side. Keeps the large probe relation A as the single
        probe spine so every join above it stays layout-preserving and
        the engine's direct-address / clustered-FK paths apply (the
        reference reaches the same shapes through bushy-tree costing in
        sql/optimizer/ob_join_order.cpp; here the right-deep shape is the
        one whose joins all ride gathers instead of sorts)."""
        if not isinstance(op, JoinOp):
            if hasattr(op, "child"):
                return replace(op, child=self._rotate_right_deep(op.child))
            return op
        op = replace(
            op,
            left=self._rotate_right_deep(op.left),
            right=self._rotate_right_deep(op.right),
        )
        while True:
            j1 = op.left
            if not (
                op.kind in ("inner", "semi", "anti")
                and op.left_keys
                and isinstance(j1, JoinOp)
                and j1.kind == "inner"
                and j1.left_keys
            ):
                break
            a_names = set(output_schema(j1.left).names())
            b_names = set(output_schema(j1.right).names())
            refs: set[str] = set()
            for e in op.left_keys:
                refs |= set(E.referenced_columns(e))
            res_refs = (
                set(E.referenced_columns(op.residual))
                if op.residual is not None
                else set()
            )
            if not (refs <= b_names and not (res_refs & a_names)):
                break
            if self._est_op(j1.left) <= self._est_op(j1.right):
                break
            inner = JoinOp(
                op.kind, j1.right, op.right,
                op.left_keys, op.right_keys, op.residual,
            )
            op = replace(j1, right=self._rotate_right_deep(inner))
        return op


def _null_rejecting_cols(c: E.Expr) -> set[str]:
    """Columns a conjunct provably rejects NULLs on: comparisons,
    BETWEEN and IN yield NULL for NULL inputs (rows dropped by
    compile_predicate); IS NULL / OR / NOT are NOT null-rejecting."""
    if isinstance(c, E.Compare):
        out = set()
        for side in (c.left, c.right):
            if isinstance(side, E.ColRef):
                out.add(side.name)
        return out
    if isinstance(c, E.Between) and not c.negated:
        return {c.arg.name} if isinstance(c.arg, E.ColRef) else set()
    if isinstance(c, E.InList) and not c.negated:
        return {c.arg.name} if isinstance(c.arg, E.ColRef) else set()
    if isinstance(c, E.IsNull) and c.negated:  # IS NOT NULL
        return {c.arg.name} if isinstance(c.arg, E.ColRef) else set()
    return set()


def _rename_cols(e: E.Expr, mapping: dict[str, str]) -> E.Expr:
    sub = {E.ColRef(old): E.ColRef(new) for old, new in mapping.items()}
    return _substitute(e, sub)


def _select_has_agg(sel: A.Select) -> bool:
    def walk(n) -> bool:
        if isinstance(n, (A.ScalarSubquery, A.ExistsOp)):
            return False  # nested subqueries have their own scope
        if isinstance(n, A.InOp) and n.subquery is not None:
            return False
        if isinstance(n, A.FuncCall) and n.name in (
            "sum", "count", "min", "max", "avg", "approx_count_distinct",
        ):
            return True
        for attr in getattr(n, "__dataclass_fields__", {}):
            v = getattr(n, attr)
            if isinstance(v, A.Node) and walk(v):
                return True
            if isinstance(v, tuple):
                for x in v:
                    if isinstance(x, A.Node) and walk(x):
                        return True
                    if (
                        isinstance(x, tuple)
                        and any(isinstance(y, A.Node) and walk(y) for y in x)
                    ):
                        return True
        return False

    return any(walk(i.expr) for i in sel.items)


def _substitute_out(e: E.Expr, out_items: list[tuple[str, E.Expr]]) -> E.Expr:
    for n, oe in out_items:
        if e == oe:
            return E.ColRef(n)
    return e


def _default_name(node: A.Node, i: int) -> str:
    if isinstance(node, A.Name):
        return node.parts[-1]
    return f"$col{i}"


def _substitute(e: E.Expr, sub: dict[E.Expr, E.Expr]) -> E.Expr:
    if e in sub:
        return sub[e]
    if isinstance(e, E.BinaryOp):
        return E.BinaryOp(e.op, _substitute(e.left, sub), _substitute(e.right, sub))
    if isinstance(e, E.Compare):
        return E.Compare(e.op, _substitute(e.left, sub), _substitute(e.right, sub))
    if isinstance(e, E.BoolOp):
        return E.BoolOp(e.op, tuple(_substitute(a, sub) for a in e.args))
    if isinstance(e, E.Not):
        return E.Not(_substitute(e.arg, sub))
    if isinstance(e, E.Cast):
        return E.Cast(_substitute(e.arg, sub), e.dtype)
    if isinstance(e, E.Case):
        return E.Case(
            tuple((_substitute(c, sub), _substitute(v, sub)) for c, v in e.whens),
            _substitute(e.default, sub) if e.default is not None else None,
        )
    if isinstance(e, E.Func):
        return E.Func(e.name, tuple(_substitute(a, sub) for a in e.args))
    if isinstance(e, E.Between):
        return E.Between(
            _substitute(e.arg, sub),
            _substitute(e.low, sub),
            _substitute(e.high, sub),
            e.negated,
        )
    if isinstance(e, E.InList):
        return E.InList(_substitute(e.arg, sub), e.values, e.negated)
    if isinstance(e, E.IsNull):
        return E.IsNull(_substitute(e.arg, sub), e.negated)
    return e
