"""Query planner: AST -> resolved, rewritten, join-ordered logical plan.

Reference surfaces:
- rewrite: the 82-rule transformer (src/sql/rewrite/ob_transformer_impl.h).
  Round-1 rules: conjunct splitting, equi-join extraction, predicate
  pushdown to scans, projection pruning, constant-comparison folding.
- optimizer: CBO join ordering (src/sql/optimizer/ob_join_order.h) —
  here a greedy connected-subgraph heuristic on estimated filtered
  cardinalities (dimension tables join first, build side = smaller input),
  which reproduces the canonical TPC-H plans without a full DP search.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..core.dtypes import Schema
from ..expr import ir as E
from . import ast as A
from .logical import (
    Aggregate,
    Distinct,
    Filter,
    JoinOp,
    Limit,
    LogicalOp,
    Project,
    ResolveError,
    Resolver,
    Scan,
    Sort,
    output_schema,
)


@dataclass
class PlannedQuery:
    plan: LogicalOp
    output_names: tuple[str, ...]


def split_conjuncts(e: E.Expr | None) -> list[E.Expr]:
    if e is None:
        return []
    if isinstance(e, E.BoolOp) and e.op == "and":
        out = []
        for a in e.args:
            out.extend(split_conjuncts(a))
        return out
    return [e]


def hoist_common_or_conjuncts(e: E.Expr) -> list[E.Expr]:
    """OR(a&b&c, a&d) -> [a, OR(b&c, d)] — factors conjuncts common to every
    OR branch so join keys and single-table filters buried in OR arms (TPC-H
    Q19 shape) become visible to pushdown/join extraction. (Reference: the
    or-expansion transform family, sql/rewrite/ob_transform_or_expansion.*.)
    """
    if not (isinstance(e, E.BoolOp) and e.op == "or"):
        return [e]
    branches = [split_conjuncts(b) for b in e.args]
    common = [c for c in branches[0] if all(c in b for b in branches[1:])]
    if not common:
        return [e]
    rest_branches = []
    for b in branches:
        rest = [c for c in b if c not in common]
        rest_branches.append(
            E.and_(*rest) if rest else E.lit(True)
        )
    if any(isinstance(rb, E.Literal) for rb in rest_branches):
        return common
    return common + [E.or_(*rest_branches)]


def _tables_of(e: E.Expr) -> set[str]:
    return {n.split(".", 1)[0] for n in E.referenced_columns(e)}


def _is_equi_join(e: E.Expr) -> tuple[E.ColRef, E.ColRef] | None:
    if (
        isinstance(e, E.Compare)
        and e.op in ("=", "==")
        and isinstance(e.left, E.ColRef)
        and isinstance(e.right, E.ColRef)
    ):
        lt = e.left.name.split(".", 1)[0]
        rt = e.right.name.split(".", 1)[0]
        if lt != rt:
            return e.left, e.right
    return None


class Planner:
    def __init__(self, catalog, stats=None):
        self.catalog = catalog  # name -> Table
        self.stats = stats or {}

    # -- cardinality guesses ------------------------------------------
    def _scan_rows(self, scan: Scan) -> float:
        base = self.catalog[scan.table].nrows or 1
        if scan.pushed_filter is not None:
            n_conj = len(split_conjuncts(scan.pushed_filter))
            base = base * (0.25 ** min(n_conj, 3))
        return max(base, 1.0)

    def plan(self, sel: A.Select, outer: Resolver | None = None) -> PlannedQuery:
        r = Resolver({n: t for n, t in self.catalog.items()}, outer)

        # ---- FROM: collect scans + structured join conditions --------
        scans: list[Scan] = []
        join_conds: list[E.Expr] = []

        def add_from(node: A.Node):
            if isinstance(node, A.TableRef):
                alias = node.alias or node.name
                scans.append(r.add_table(node.name, alias))
            elif isinstance(node, A.Join):
                if node.kind != "inner":
                    raise ResolveError(
                        f"{node.kind} join not yet supported by the planner"
                    )
                add_from(node.left)
                add_from(node.right)
                if node.on is not None:
                    join_conds.extend(split_conjuncts(r.expr(node.on)))
            elif isinstance(node, A.SubqueryRef):
                raise ResolveError("FROM subqueries not yet supported")
            else:
                raise ResolveError(f"bad FROM item {node!r}")

        for f in sel.from_:
            add_from(f)

        # ---- WHERE ----------------------------------------------------
        where_conjs = join_conds + (
            split_conjuncts(r.expr(sel.where)) if sel.where is not None else []
        )
        where_conjs = [
            h for c in where_conjs for h in hoist_common_or_conjuncts(c)
        ]

        # classify: single-table -> pushdown; equi-join; residual
        by_alias = {s.alias: s for s in scans}
        equi: list[tuple[E.ColRef, E.ColRef]] = []
        residual: list[E.Expr] = []
        for c in where_conjs:
            tabs = _tables_of(c)
            ej = _is_equi_join(c)
            if ej is not None:
                equi.append(ej)
            elif len(tabs) == 1 and next(iter(tabs)) in by_alias:
                s = by_alias[next(iter(tabs))]
                s.pushed_filter = (
                    c
                    if s.pushed_filter is None
                    else E.and_(s.pushed_filter, c)
                )
            else:
                residual.append(c)

        # ---- join order (greedy, smallest filtered input first) -------
        plan = self._order_joins(scans, equi, residual)

        # ---- GROUP BY / aggregates ------------------------------------
        alias_map: dict[str, E.Expr] = {}
        group_nodes = list(sel.group_by)
        has_agg_in_select = _select_has_agg(sel)
        agg_order_keys: list[tuple[E.Expr, bool]] | None = None
        if group_nodes or has_agg_in_select or sel.having is not None:
            key_exprs = []
            for i, g in enumerate(group_nodes):
                ge = r.expr(g)
                name = (
                    ge.name
                    if isinstance(ge, E.ColRef)
                    else f"$gkey{i}"
                )
                key_exprs.append((name, ge))
            # resolve select items, having AND order-by with aggregates
            # allowed BEFORE building the Aggregate node, so every agg call
            # anywhere in the query lands in r.agg_exprs.
            out_items = []
            for i, item in enumerate(sel.items):
                e = r.expr(item.expr, allow_agg=True)
                name = item.alias or _default_name(item.expr, i)
                out_items.append((name, e))
                alias_map[name] = e
            having_e = (
                r.expr(sel.having, allow_agg=True)
                if sel.having is not None
                else None
            )
            agg_order_keys = []
            for oi in sel.order_by:
                if (
                    isinstance(oi.expr, A.Name)
                    and len(oi.expr.parts) == 1
                    and oi.expr.parts[0] in alias_map
                ):
                    agg_order_keys.append((E.ColRef(oi.expr.parts[0]), oi.descending))
                elif isinstance(oi.expr, A.NumberLit):
                    agg_order_keys.append(
                        (E.ColRef(out_items[int(oi.expr.value) - 1][0]), oi.descending)
                    )
                else:
                    oe = r.expr(oi.expr, allow_agg=True)
                    matched = [n for n, e2 in out_items if e2 == oe]
                    agg_order_keys.append(
                        (E.ColRef(matched[0]) if matched else oe, oi.descending)
                    )
            plan = Aggregate(plan, tuple(key_exprs), tuple(r.agg_exprs))
            # rewrite out_items/having over the aggregate's output schema:
            # group keys keep their names; $aggN are columns now.
            sub = {e: E.ColRef(n) for n, e in key_exprs}
            out_items = [(n, _substitute(e, sub)) for n, e in out_items]
            if having_e is not None:
                having_e = _substitute(having_e, sub)
                plan = Filter(plan, having_e)
        else:
            out_items = []
            for i, item in enumerate(sel.items):
                if isinstance(item.expr, A.Star):
                    s = output_schema(plan)
                    for f in s.fields:
                        short = f.name.split(".", 1)[1] if "." in f.name else f.name
                        out_items.append((short, E.ColRef(f.name)))
                        alias_map[short] = E.ColRef(f.name)
                    continue
                e = r.expr(item.expr)
                name = item.alias or _default_name(item.expr, i)
                out_items.append((name, e))
                alias_map[name] = e

        # ---- ORDER BY (resolves select aliases, then input columns) ---
        if agg_order_keys is not None:
            order_keys = [
                (_substitute_out(e, out_items), d) for e, d in agg_order_keys
            ]
        else:
            order_keys = []
            for oi in sel.order_by:
                if (
                    isinstance(oi.expr, A.Name)
                    and len(oi.expr.parts) == 1
                    and oi.expr.parts[0] in alias_map
                ):
                    oe = E.ColRef(oi.expr.parts[0])
                elif isinstance(oi.expr, A.NumberLit):
                    oe = E.ColRef(out_items[int(oi.expr.value) - 1][0])
                else:
                    oe = r.expr(oi.expr)
                    matched = [n for n, e in out_items if e == oe]
                    oe = E.ColRef(matched[0]) if matched else oe
                order_keys.append((oe, oi.descending))

        # order-by exprs not expressible over the projected outputs ride as
        # hidden projection columns (dropped from the visible result)
        visible = tuple(n for n, _ in out_items)
        fixed_order = []
        for i, (oe, d) in enumerate(order_keys):
            if isinstance(oe, E.ColRef) and any(n == oe.name for n, _ in out_items):
                fixed_order.append((oe, d))
            else:
                if sel.distinct:
                    # a hidden sort column would become part of the DISTINCT
                    # key and silently un-dedupe rows (SQL standard requires
                    # ORDER BY items to appear in the DISTINCT select list)
                    raise ResolveError(
                        "ORDER BY expression must appear in the select list "
                        "of a SELECT DISTINCT"
                    )
                hidden = f"$ord{i}"
                out_items.append((hidden, oe))
                fixed_order.append((E.ColRef(hidden), d))
        order_keys = fixed_order

        plan = Project(plan, tuple(out_items))
        if sel.distinct:
            plan = Distinct(plan)
        if order_keys:
            plan = Sort(plan, tuple(order_keys))
        if sel.limit is not None:
            plan = Limit(plan, sel.limit, sel.offset or 0)

        return PlannedQuery(plan, visible)

    def _order_joins(
        self,
        scans: list[Scan],
        equi: list[tuple[E.ColRef, E.ColRef]],
        residual: list[E.Expr],
    ) -> LogicalOp:
        if not scans:
            raise ResolveError("SELECT without FROM is not supported")
        if len(scans) == 1:
            plan: LogicalOp = scans[0]
            return plan
        remaining = {s.alias: s for s in scans}
        sizes = {s.alias: self._scan_rows(s) for s in scans}
        # start from the largest table (the fact side stays the probe side)
        start = max(sizes, key=lambda a: sizes[a])
        joined = {start}
        plan = remaining.pop(start)
        pending_equi = list(equi)
        while remaining:
            # candidate tables connected to the joined set
            best = None
            for alias, s in remaining.items():
                keys = [
                    (l, r_)
                    for l, r_ in pending_equi
                    if (
                        l.name.split(".")[0] in joined
                        and r_.name.split(".")[0] == alias
                    )
                    or (
                        r_.name.split(".")[0] in joined
                        and l.name.split(".")[0] == alias
                    )
                ]
                if not keys:
                    continue
                if best is None or sizes[alias] < sizes[best[0]]:
                    best = (alias, keys)
            if best is None:
                # cross join fallback: smallest remaining
                alias = min(remaining, key=lambda a: sizes[a])
                plan = JoinOp("cross", plan, remaining.pop(alias))
                joined.add(alias)
                continue
            alias, keys = best
            lkeys, rkeys = [], []
            for l, r_ in keys:
                if l.name.split(".")[0] == alias:
                    l, r_ = r_, l
                lkeys.append(l)
                rkeys.append(r_)
                pending_equi.remove(
                    (l, r_) if (l, r_) in pending_equi else (r_, l)
                )
            plan = JoinOp(
                "inner",
                plan,
                remaining.pop(alias),
                tuple(lkeys),
                tuple(rkeys),
            )
            joined.add(alias)
        # leftover equi conds (cycles) + residuals become filters on top
        leftover = [E.Compare("=", l, r_) for l, r_ in pending_equi] + residual
        for c in leftover:
            plan = Filter(plan, c)
        return plan


def _select_has_agg(sel: A.Select) -> bool:
    def walk(n) -> bool:
        if isinstance(n, A.FuncCall) and n.name in (
            "sum", "count", "min", "max", "avg",
        ):
            return True
        for attr in getattr(n, "__dataclass_fields__", {}):
            v = getattr(n, attr)
            if isinstance(v, A.Node) and walk(v):
                return True
            if isinstance(v, tuple):
                for x in v:
                    if isinstance(x, A.Node) and walk(x):
                        return True
                    if (
                        isinstance(x, tuple)
                        and any(isinstance(y, A.Node) and walk(y) for y in x)
                    ):
                        return True
        return False

    return any(walk(i.expr) for i in sel.items)


def _substitute_out(e: E.Expr, out_items: list[tuple[str, E.Expr]]) -> E.Expr:
    """Rewrite an agg-schema expr into projection-output space where an
    identical expression is already projected."""
    for n, oe in out_items:
        if e == oe:
            return E.ColRef(n)
    return e


def _default_name(node: A.Node, i: int) -> str:
    if isinstance(node, A.Name):
        return node.parts[-1]
    return f"$col{i}"


def _substitute(e: E.Expr, sub: dict[E.Expr, E.Expr]) -> E.Expr:
    if e in sub:
        return sub[e]
    if isinstance(e, E.BinaryOp):
        return E.BinaryOp(e.op, _substitute(e.left, sub), _substitute(e.right, sub))
    if isinstance(e, E.Compare):
        return E.Compare(e.op, _substitute(e.left, sub), _substitute(e.right, sub))
    if isinstance(e, E.BoolOp):
        return E.BoolOp(e.op, tuple(_substitute(a, sub) for a in e.args))
    if isinstance(e, E.Not):
        return E.Not(_substitute(e.arg, sub))
    if isinstance(e, E.Cast):
        return E.Cast(_substitute(e.arg, sub), e.dtype)
    if isinstance(e, E.Case):
        return E.Case(
            tuple((_substitute(c, sub), _substitute(v, sub)) for c, v in e.whens),
            _substitute(e.default, sub) if e.default is not None else None,
        )
    if isinstance(e, E.Func):
        return E.Func(e.name, tuple(_substitute(a, sub) for a in e.args))
    if isinstance(e, E.Between):
        return E.Between(
            _substitute(e.arg, sub),
            _substitute(e.low, sub),
            _substitute(e.high, sub),
            e.negated,
        )
    if isinstance(e, E.InList):
        return E.InList(_substitute(e.arg, sub), e.values, e.negated)
    if isinstance(e, E.IsNull):
        return E.IsNull(_substitute(e.arg, sub), e.negated)
    return e
