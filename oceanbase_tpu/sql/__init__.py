from . import ast, logical, parser, planner

__all__ = ["ast", "logical", "parser", "planner"]
