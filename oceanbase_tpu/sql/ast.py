"""SQL AST nodes.

Reference surface: the parse-node layer (src/sql/parser/parse_node.h) that
the flex/bison grammar produces. The rebuild uses a hand-written recursive
descent parser (sql/parser.py) over these dataclasses; the grammar subset
covers the analytic SQL the TPC-H/TPC-DS suites need and grows toward full
MySQL-compatible DML.
"""

from __future__ import annotations

from dataclasses import dataclass, field


class Node:
    __slots__ = ()


# ---- scalar expressions ---------------------------------------------------


@dataclass(frozen=True)
class Name(Node):
    """Possibly-qualified column reference: l_orderkey or l.l_orderkey."""

    parts: tuple[str, ...]

    def __str__(self):
        return ".".join(self.parts)


@dataclass(frozen=True)
class NumberLit(Node):
    value: str  # textual, typed later (int vs decimal)


@dataclass(frozen=True)
class StringLit(Node):
    value: str


@dataclass(frozen=True)
class DateLit(Node):
    value: str  # 'YYYY-MM-DD'


@dataclass(frozen=True)
class IntervalLit(Node):
    value: str
    unit: str  # day | month | year


@dataclass(frozen=True)
class Star(Node):
    pass


@dataclass(frozen=True)
class UnaryOp(Node):
    op: str  # '-' | 'not'
    operand: Node


@dataclass(frozen=True)
class BinOp(Node):
    op: str  # + - * / % = != <> < <= > >= and or
    left: Node
    right: Node


@dataclass(frozen=True)
class BetweenOp(Node):
    expr: Node
    low: Node
    high: Node
    negated: bool = False


@dataclass(frozen=True)
class InOp(Node):
    expr: Node
    items: tuple[Node, ...] | None  # literal list
    subquery: "Select | None" = None
    negated: bool = False


@dataclass(frozen=True)
class LikeOp(Node):
    expr: Node
    pattern: Node
    negated: bool = False


@dataclass(frozen=True)
class IsNullOp(Node):
    expr: Node
    negated: bool = False


@dataclass(frozen=True)
class ExistsOp(Node):
    subquery: "Select"
    negated: bool = False


@dataclass(frozen=True)
class ScalarSubquery(Node):
    subquery: "Select"


@dataclass(frozen=True)
class FuncCall(Node):
    name: str
    args: tuple[Node, ...]
    distinct: bool = False  # count(distinct x)


@dataclass(frozen=True)
class WindowCall(Node):
    """func(args) OVER (PARTITION BY ... ORDER BY ...).

    Reference surface: the window-function resolver/operator
    (src/sql/resolver/expr win_func items, src/sql/engine/window_function).
    Frames: the SQL-default frame only (RANGE UNBOUNDED PRECEDING..CURRENT
    ROW with ORDER BY; the whole partition without)."""

    name: str  # ranking | aggregate | lag/lead | ntile | first/last_value
    args: tuple[Node, ...]
    partition_by: tuple[Node, ...] = ()
    order_by: tuple["OrderItem", ...] = ()
    # (unit, lo, hi): unit in {rows, range}; bounds are signed offsets
    # (negative = PRECEDING, 0 = CURRENT ROW, None = UNBOUNDED that way)
    frame: tuple | None = None


@dataclass(frozen=True)
class ExtractOp(Node):
    field_: str  # year | month | day
    expr: Node


@dataclass(frozen=True)
class SubstringOp(Node):
    expr: Node
    start: Node
    length: Node | None


@dataclass(frozen=True)
class CaseOp(Node):
    whens: tuple[tuple[Node, Node], ...]
    default: Node | None


@dataclass(frozen=True)
class CastOp(Node):
    expr: Node
    type_name: str  # 'decimal(12,2)' | 'date' | 'integer' ...


# ---- relational -----------------------------------------------------------


@dataclass(frozen=True)
class TableRef(Node):
    name: str
    alias: str | None = None
    # FLASHBACK read: AS OF SNAPSHOT <ts> (None = current snapshot)
    snapshot: int | None = None


@dataclass(frozen=True)
class SubqueryRef(Node):
    subquery: "Select"
    alias: str


@dataclass(frozen=True)
class Join(Node):
    kind: str  # inner | left | right | full | cross
    left: Node
    right: Node
    on: Node | None


@dataclass(frozen=True)
class SelectItem(Node):
    expr: Node
    alias: str | None = None


@dataclass(frozen=True)
class OrderItem(Node):
    expr: Node
    descending: bool = False


@dataclass(frozen=True)
class Select(Node):
    items: tuple[SelectItem, ...]
    from_: tuple[Node, ...] = ()  # TableRef | SubqueryRef | Join
    where: Node | None = None
    group_by: tuple[Node, ...] = ()
    having: Node | None = None
    order_by: tuple[OrderItem, ...] = ()
    limit: int | None = None
    offset: int | None = None
    distinct: bool = False
    ctes: tuple[tuple[str, "Select"], ...] = ()  # WITH name AS (...)
    # grouping sets: index tuples into group_by (ROLLUP/CUBE/GROUPING
    # SETS expansion); None = plain GROUP BY
    group_sets: tuple[tuple[int, ...], ...] | None = None
    # names of ctes that are WITH RECURSIVE (subset of ctes keys)
    recursive_ctes: tuple[str, ...] = ()


@dataclass(frozen=True)
class SetSelect(Node):
    """Set operation between two query expressions.

    Reference surface: the set-operator resolvers/operators
    (src/sql/resolver/set, src/sql/engine/set — hash union/intersect/
    except). ORDER BY / LIMIT apply to the combined result; output column
    names come from the left side."""

    kind: str  # union | intersect | except
    all: bool
    left: "Select | SetSelect"
    right: "Select | SetSelect"
    order_by: tuple[OrderItem, ...] = ()
    limit: int | None = None
    offset: int | None = None
    ctes: tuple[tuple[str, "Select"], ...] = ()
    recursive_ctes: tuple[str, ...] = ()


# ---- statements (DDL / DML / tx control) ----------------------------------
# Reference surface: the DDL/DML resolvers under src/sql/resolver/{ddl,dml}
# (ObCreateTableStmt, ObInsertStmt, ObUpdateStmt, ObDeleteStmt) and the tx
# control statements handled by ObSqlTransControl (sql/ob_sql_trans_control).


@dataclass(frozen=True)
class ColumnDef(Node):
    name: str
    type_name: str  # as written: 'bigint' | 'decimal(12,2)' | 'varchar' ...
    not_null: bool = False


@dataclass(frozen=True)
class CreateTable(Node):
    name: str
    columns: tuple[ColumnDef, ...]
    primary_key: tuple[str, ...]  # empty -> first column
    if_not_exists: bool = False
    # PARTITION BY HASH(col) PARTITIONS n (reference: hash-partitioned
    # tables; each partition is a tablet placed on a log stream)
    partition_by: str | None = None
    n_partitions: int = 1


@dataclass(frozen=True)
class DropTable(Node):
    name: str
    if_exists: bool = False


@dataclass(frozen=True)
class CreateIndex(Node):
    """CREATE [UNIQUE] INDEX name ON table (cols...). Reference surface:
    the DDL resolver + direct-insert index build (src/storage/ddl)."""

    name: str
    table: str
    columns: tuple[str, ...]
    unique: bool = False
    if_not_exists: bool = False


@dataclass(frozen=True)
class DropIndex(Node):
    name: str
    table: str
    if_exists: bool = False


@dataclass(frozen=True)
class Insert(Node):
    table: str
    columns: tuple[str, ...]  # empty -> full schema order
    rows: tuple[tuple[Node, ...], ...] = ()  # literal/expr tuples
    select: "Select | None" = None  # INSERT ... SELECT


@dataclass(frozen=True)
class Update(Node):
    table: str
    assignments: tuple[tuple[str, Node], ...]  # (column, expr)
    where: Node | None = None


@dataclass(frozen=True)
class Delete(Node):
    table: str
    where: Node | None = None


@dataclass(frozen=True)
class AlterSystemSet(Node):
    """ALTER SYSTEM SET name = value (config hot reload)."""

    name: str
    value: str


@dataclass(frozen=True)
class RunLayoutAdvisor(Node):
    """ALTER SYSTEM RUN LAYOUT ADVISOR (one advisor pass now; applies
    only when ob_layout_advisor_mode=auto, else dry-run)."""


@dataclass(frozen=True)
class Show(Node):
    """SHOW PARAMETERS [LIKE 'pat'] | SHOW TABLES."""

    what: str
    like: str | None = None


@dataclass(frozen=True)
class LockTable(Node):
    """LOCK TABLE name IN SHARE|EXCLUSIVE MODE (tx-scoped, tablelock)."""

    name: str
    exclusive: bool


@dataclass(frozen=True)
class CreateTrigger(Node):
    """CREATE TRIGGER name {BEFORE|AFTER} {INSERT|UPDATE|DELETE} ON table
    FOR EACH ROW <body> (ob_trigger_resolver.cpp analog; body grammar in
    sql/trigger.py)."""

    name: str
    timing: str  # before | after
    event: str  # insert | update | delete
    table: str
    body_sql: str


@dataclass(frozen=True)
class DropTrigger(Node):
    name: str


@dataclass(frozen=True)
class CreateView(Node):
    """CREATE [OR REPLACE] VIEW name AS <select text> — a PLAIN view:
    only the definition text persists; every query referencing it expands
    the text at plan time (merged into the outer block when the body is
    simple select-project-join — ob_transform_view_merge analog)."""

    name: str
    query_sql: str
    or_replace: bool = False


@dataclass(frozen=True)
class DropView(Node):
    name: str


@dataclass(frozen=True)
class CreateMaterializedView(Node):
    """CREATE MATERIALIZED VIEW name AS <select text> — materialized at
    creation; REFRESH re-runs the defining query (full refresh, the
    mview core; reference: src/storage/mview)."""

    name: str
    query_sql: str


@dataclass(frozen=True)
class DropMaterializedView(Node):
    name: str


@dataclass(frozen=True)
class RefreshMaterializedView(Node):
    name: str


@dataclass(frozen=True)
class CreateExternalTable(Node):
    """CREATE EXTERNAL TABLE name USING format LOCATION 'path' — schema
    inferred from the file via the plugin loader registry."""

    name: str
    format: str
    location: str


@dataclass(frozen=True)
class CreateVectorIndex(Node):
    """CREATE VECTOR INDEX name ON table (column) [WITH (lists=N,
    nprobe=M)] — IVF-flat ANN index (storage/vector_index.py)."""

    name: str
    table: str
    column: str
    lists: int = 0
    nprobe: int = 8


@dataclass(frozen=True)
class DropVectorIndex(Node):
    name: str
    table: str
    column: str


@dataclass(frozen=True)
class CreateUser(Node):
    """CREATE USER name [IDENTIFIED BY 'password']."""

    name: str
    password: str = ""


@dataclass(frozen=True)
class DropUser(Node):
    name: str


@dataclass(frozen=True)
class Grant(Node):
    """GRANT priv[, priv] ON table|* TO user. Privileges lowercase;
    'all' expands server-side."""

    privs: tuple[str, ...]
    obj: str
    user: str


@dataclass(frozen=True)
class Revoke(Node):
    privs: tuple[str, ...]
    obj: str
    user: str


@dataclass(frozen=True)
class KillQuery(Node):
    """KILL [QUERY] <session_id>: interrupt the session's running
    statement cluster-wide (share/interrupt analog)."""

    session_id: int


@dataclass(frozen=True)
class Begin(Node):
    pass


@dataclass(frozen=True)
class Commit(Node):
    pass


@dataclass(frozen=True)
class Rollback(Node):
    pass


Statement = Node  # any of the above or Select


def rewrite(node, fn):
    """Generic top-down AST rewrite: `fn(node)` returns a replacement node
    (stopping descent there) or None to keep walking. Non-Node values and
    tuples (including one level of nested tuples, e.g. CTE pairs) pass
    through structurally. Shared by trigger NEW/OLD substitution and the
    planner's view-merge requalification — one walker to maintain."""
    if isinstance(node, Node):
        r = fn(node)
        if r is not None:
            return r
    if not isinstance(node, Node):
        return node
    from dataclasses import replace as _rep

    def val(v):
        if isinstance(v, Node):
            return rewrite(v, fn)
        if isinstance(v, tuple):
            return tuple(val(x) for x in v)
        return v

    kw = {}
    for fld in node.__dataclass_fields__:
        v = getattr(node, fld)
        v2 = val(v)
        if v2 is not v:
            kw[fld] = v2
    return _rep(node, **kw) if kw else node
