"""LogStore: the disk log engine behind a palf replica.

Reference surface: logservice/palf's LogEngine = LogStorage (fixed-size
block files of group entries, log_engine.h) + LogIOWorker (ordered appends
with batched sync, log_io_worker.h), plus the durable vote/term state the
election code keeps (palf persists proposal ids and membership meta through
LogMetaStorage). The rebuild keeps the same split at test scale:

  * segment files `seg_XXXXXXXX.plog` of fixed entry count — dense LSNs
    make segment membership arithmetic (lsn // SEGMENT_ENTRIES), the analog
    of PALF's fixed 64MB blocks (log_define.h:67);
  * appends are buffered and made durable by `sync()` — the group-commit
    point. A replica MUST sync before acking an append or counting its own
    log in a commit quorum (raft durability rule; the reference achieves
    it by acking from the IO worker's completion path);
  * `meta` file holds (term, voted_for), replaced atomically + fsynced
    BEFORE any message that promises the vote/term (a vote that survives
    restart is what makes double-voting impossible);
  * crash recovery truncates a torn final record at load;
  * `recycle(upto_lsn)` deletes whole segments strictly below the
    checkpoint point (slog_ckpt advancing the palf recycle point).

Record format: `<q lsn><q term><q scn><I payload_len><I crc32>payload`.
"""

from __future__ import annotations

import os
import struct
import zlib

from .palf import LogEntry

_REC = struct.Struct("<qqqII")
SEGMENT_ENTRIES = 8192


def scan_records(buf: bytes) -> tuple[list[tuple[int, int, int, bytes]], int]:
    """Parse `<q lsn><q term><q scn><I len><I crc>payload` records from buf.

    Returns ([(lsn, term, scn, payload), ...], good_end): whole, crc-valid
    records and the byte offset of the last valid boundary. A torn or
    corrupt tail simply ends the scan — the ONE shared implementation of
    crash-boundary detection for the log store and the archive (divergent
    copies of this loop invite divergent crash behavior)."""
    recs = []
    pos = 0
    n = len(buf)
    while pos + _REC.size <= n:
        lsn, term, scn, plen, crc = _REC.unpack_from(buf, pos)
        end = pos + _REC.size + plen
        if plen < 0 or end > n:
            break
        payload = bytes(buf[pos + _REC.size : end])
        if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
            break
        recs.append((lsn, term, scn, payload))
        pos = end
    return recs, pos


class LogStore:
    """Durable storage of one replica's log + election meta."""

    def __init__(self, root: str, fsync: bool = True):
        self.root = root
        self.fsync = fsync
        os.makedirs(root, exist_ok=True)
        self._meta_path = os.path.join(root, "meta")
        # open tail file handle (append mode), lazily (re)opened
        self._tail_fh = None
        self._tail_seg = -1
        self._dirty = False
        # cached meta fields (term, voted_for, recycle-point info)
        self._term = 0
        self._voted_for: int | None = None
        self.base_prev_lsn = -1
        self.base_prev_term = 0

    # ------------------------------------------------------------- paths
    def _seg_path(self, seg: int) -> str:
        return os.path.join(self.root, f"seg_{seg:08d}.plog")

    def _segments(self) -> list[int]:
        return sorted(
            int(f[4:-5]) for f in os.listdir(self.root)
            if f.startswith("seg_") and f.endswith(".plog")
        )

    # -------------------------------------------------------------- load
    def load(self) -> tuple[list[LogEntry], int, int, int | None]:
        """Scan all segments; returns (entries, base_lsn, term, voted_for).

        Torn final records (crash mid-append) are truncated. Entries are
        contiguous from base_lsn (the first LSN still on disk after
        recycling)."""
        term, voted_for = 0, None
        if os.path.exists(self._meta_path):
            with open(self._meta_path) as f:
                parts = f.read().split()
            term = int(parts[0])
            voted_for = None if parts[1] == "-" else int(parts[1])
            if len(parts) >= 4:
                self.base_prev_lsn = int(parts[2])
                self.base_prev_term = int(parts[3])
        self._term, self._voted_for = term, voted_for
        entries: list[LogEntry] = []
        segs = self._segments()
        for i, seg in enumerate(segs):
            path = self._seg_path(seg)
            with open(path, "rb") as f:
                buf = f.read()
            recs, pos = scan_records(buf)
            entries.extend(LogEntry(*r) for r in recs)
            if pos < len(buf):
                # torn/corrupt tail: only legal on the LAST segment; chop it
                with open(path, "r+b") as f:
                    f.truncate(pos)
                # anything recorded in later segments was written after the
                # torn record and is unreachable — drop those files
                for later in segs[i + 1 :]:
                    os.remove(self._seg_path(later))
                break
        base_lsn = entries[0].lsn if entries else (
            segs[0] * SEGMENT_ENTRIES if segs else 0
        )
        return entries, base_lsn, term, voted_for

    # ------------------------------------------------------------ append
    def append(self, entries) -> None:
        """Buffered append in LSN order; call sync() to make durable."""
        for e in entries:
            seg = e.lsn // SEGMENT_ENTRIES
            if seg != self._tail_seg or self._tail_fh is None:
                self._roll_to(seg)
            self._tail_fh.write(
                _REC.pack(e.lsn, e.term, e.scn, len(e.payload),
                          zlib.crc32(e.payload) & 0xFFFFFFFF)
            )
            self._tail_fh.write(e.payload)
            self._dirty = True

    def _roll_to(self, seg: int) -> None:
        if self._tail_fh is not None:
            self._tail_fh.flush()
            if self.fsync:
                os.fsync(self._tail_fh.fileno())
            self._tail_fh.close()
        self._tail_fh = open(self._seg_path(seg), "ab")
        self._tail_seg = seg

    def sync(self) -> None:
        """Group-commit point: flush buffered appends to disk."""
        if self._tail_fh is not None and self._dirty:
            self._tail_fh.flush()
            if self.fsync:
                os.fsync(self._tail_fh.fileno())
            self._dirty = False

    # ---------------------------------------------------------- truncate
    def truncate_from(self, lsn: int) -> None:
        """Remove entries >= lsn (conflicting-suffix reconciliation)."""
        if self._tail_fh is not None:
            self._tail_fh.flush()
            self._tail_fh.close()
            self._tail_fh = None
            self._tail_seg = -1
        seg = lsn // SEGMENT_ENTRIES
        for s in self._segments():
            if s > seg:
                os.remove(self._seg_path(s))
        path = self._seg_path(seg)
        if not os.path.exists(path):
            return
        with open(path, "rb") as f:
            buf = f.read()
        pos = 0
        for elsn, _t, _s, payload in scan_records(buf)[0]:
            if elsn >= lsn:
                break
            pos += _REC.size + len(payload)
        if pos == 0:
            os.remove(path)
        else:
            with open(path, "r+b") as f:
                f.truncate(pos)

    # ----------------------------------------------------------- recycle
    def recycle(self, upto_lsn: int) -> int:
        """Delete whole segments entirely below upto_lsn (all entries are
        covered by a durable checkpoint). Returns segments removed. The
        tail segment is never removed (consensus keeps indexing the last
        entry for prev-term checks).

        Disk recycling is SEGMENT-aligned: the post-restart base is the
        first retained segment's start, not upto_lsn — so the durable base
        info must describe the entry just below THAT boundary (read from
        the last victim before it is deleted), or log matching at the new
        base would use a term from the wrong lsn."""
        segs = self._segments()
        victims = [
            s for s in segs[:-1] if (s + 1) * SEGMENT_ENTRIES <= upto_lsn
        ]
        if not victims:
            return 0
        new_base = (victims[-1] + 1) * SEGMENT_ENTRIES
        prev_term = self._term_of(victims[-1], new_base - 1)
        if prev_term is None:
            return 0  # boundary entry unreadable: keep everything
        self.set_base_info(new_base - 1, prev_term)  # durable BEFORE rm
        removed = 0
        for s in victims:
            os.remove(self._seg_path(s))
            removed += 1
        return removed

    def _term_of(self, seg: int, lsn: int) -> int | None:
        """Scan one segment file for the entry at lsn; returns its term."""
        path = self._seg_path(seg)
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            buf = f.read()
        for elsn, t, _s, _p in scan_records(buf)[0]:
            if elsn == lsn:
                return t
        return None

    # -------------------------------------------------------------- meta
    def save_meta(self, term: int, voted_for: int | None) -> None:
        """Atomically persist election state; durable BEFORE any message
        that acts on it (vote grants, term bumps)."""
        self._term, self._voted_for = term, voted_for
        self._write_meta()

    def set_base_info(self, prev_lsn: int, prev_term: int) -> None:
        """Record the (lsn, term) of the last entry about to be recycled so
        log matching at the new base still works after restart."""
        self.base_prev_lsn, self.base_prev_term = prev_lsn, prev_term
        self._write_meta()

    def _write_meta(self) -> None:
        from ..share.fsutil import atomic_write

        vf = "-" if self._voted_for is None else self._voted_for
        atomic_write(
            self._meta_path,
            f"{self._term} {vf} {self.base_prev_lsn} {self.base_prev_term}".encode(),
            fsync=self.fsync,
        )

    def close(self) -> None:
        self.sync()
        if self._tail_fh is not None:
            self._tail_fh.close()
            self._tail_fh = None
