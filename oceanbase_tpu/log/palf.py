"""PALF-lite: leader-based replicated append-only log.

Reference surface: logservice/palf — PalfHandleImpl::submit_log
(palf_handle_impl.cpp:411) appends into a LogSlidingWindow
(log_sliding_window.h:203) that groups entries, replicates via
LogNetService push/ack, advances committed_end_lsn on majority ack, and
hands committed logs to apply/replay services; roles come from LogStateMgr
with lease-based election (palf/election). PALF is leader-based consensus
with proposal-id-stamped logs — functionally raft-shaped — and the rebuild
implements exactly that shape:

  * dense LSNs; entries stamped with the leader's term (proposal id);
  * a bounded sliding window of in-flight entries (group replication);
  * majority ack -> commit_lsn advance -> apply callback (ordered);
  * lease election: followers refuse votes while the leader lease is live
    (prevents disruption); candidates need up-to-date logs to win;
  * log reconciliation on divergence (conflicting suffix truncated).

The state machine is pure event/tick driven — no threads, no wall clock —
so consensus invariants are tested deterministically (tests/test_palf.py);
a runtime wrapper drives it from real time in deployments.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Callable

from .transport import LocalBus


class Role(enum.Enum):
    LEADER = "leader"
    FOLLOWER = "follower"
    CANDIDATE = "candidate"


@dataclass(frozen=True)
class LogEntry:
    lsn: int
    term: int
    scn: int  # commit timestamp hint (monotonic per log)
    payload: bytes


class LogView:
    """Dense-LSN log whose prefix may have been recycled to a checkpoint.

    Presents list-like access indexed by ABSOLUTE lsn (the code's dense-LSN
    invariant: log[lsn].lsn == lsn) while physically holding only entries
    >= base. `base_prev_term` is the term of entry base-1 (needed for
    log-matching AppendReqs that start exactly at base)."""

    __slots__ = ("base", "entries", "base_prev_term")

    def __init__(self, base: int = 0, entries: list[LogEntry] | None = None,
                 base_prev_term: int = 0):
        self.base = base
        self.entries: list[LogEntry] = entries if entries is not None else []
        self.base_prev_term = base_prev_term

    def __len__(self) -> int:
        return self.base + len(self.entries)

    def __bool__(self) -> bool:
        return len(self) > 0

    def __getitem__(self, i):
        if isinstance(i, slice):
            start, stop, step = i.indices(len(self))
            if step != 1:
                raise ValueError("LogView slices are contiguous")
            lo = max(start - self.base, 0)
            hi = max(stop - self.base, 0)
            return self.entries[lo:hi]
        if i < 0:
            i += len(self)
        if i < self.base:
            raise IndexError(f"lsn {i} recycled (base {self.base})")
        return self.entries[i - self.base]

    def __delitem__(self, i) -> None:
        # only suffix deletion is meaningful for a log
        if not isinstance(i, slice) or i.stop is not None or i.step is not None:
            raise ValueError("only `del log[lsn:]` is supported")
        start = i.start if i.start >= 0 else len(self) + i.start
        if start < self.base:
            raise IndexError(f"cannot truncate below base {self.base}")
        del self.entries[start - self.base :]

    def append(self, e: LogEntry) -> None:
        self.entries.append(e)

    def term_at(self, lsn: int) -> int | None:
        """Term of entry at lsn; None if below base (recycled — committed
        by construction) or beyond the end."""
        if lsn < self.base:
            return None
        if lsn >= len(self):
            return None
        return self.entries[lsn - self.base].term


# ---- messages -----------------------------------------------------------
@dataclass(frozen=True)
class AppendReq:
    term: int
    leader_id: int
    prev_lsn: int
    prev_term: int
    entries: tuple[LogEntry, ...]
    commit_lsn: int


@dataclass(frozen=True)
class AppendAck:
    term: int
    ack_lsn: int  # highest lsn the follower has matched, -1 on mismatch
    success: bool


@dataclass(frozen=True)
class VoteReq:
    term: int
    candidate_id: int
    last_lsn: int
    last_term: int
    # leadership transfer: bypass the lease check (sent only by a candidate
    # that the old leader explicitly handed off to via TimeoutNow)
    force: bool = False


@dataclass(frozen=True)
class TimeoutNow:
    """Leader -> chosen successor: start an election immediately (the
    leadership-transfer handshake; successor's log is already caught up)."""

    term: int


@dataclass(frozen=True)
class VoteResp:
    term: int
    granted: bool


HEARTBEAT_IVL = 0.05
LEASE_TIMEOUT = 0.25
ELECTION_JITTER = 0.05
MAX_INFLIGHT = 1024  # sliding-window cap (entries per follower burst)

# membership-change log entries (LogConfigMgr analog): the payload is a
# reserved marker + the new member list; replicas adopt the config when
# the entry is APPENDED (Raft's rule), and the apply path never surfaces
# these to the state machine
CONFIG_PREFIX = b"\x00\x00CFG1:"


def _encode_config(peers: list[int]) -> bytes:
    return CONFIG_PREFIX + ",".join(str(p) for p in sorted(peers)).encode()


def _decode_config(payload: bytes) -> list[int] | None:
    if not payload.startswith(CONFIG_PREFIX):
        return None
    body = payload[len(CONFIG_PREFIX):]
    return [int(x) for x in body.split(b",") if x]


@dataclass
class PalfReplica:
    """One replica of one log stream."""

    node_id: int
    peers: list[int]  # all member ids including self
    bus: LocalBus
    on_commit: Callable[[LogEntry], None] | None = None
    # durable log engine (log/store.LogStore); None = volatile (pure unit
    # tests). With a store, every append/truncate is mirrored to disk and
    # synced BEFORE the replica acks or counts itself in a quorum, and
    # (term, voted_for) are persisted BEFORE any message acting on them.
    store: Any | None = None
    role: Role = Role.FOLLOWER
    term: int = 0
    voted_for: int | None = None
    log: LogView = field(default_factory=LogView)
    commit_lsn: int = -1
    applied_lsn: int = -1
    # scn of the newest applied entry: the replica's apply watermark in
    # the GTS timestamp domain (tx/ls.py LSReplica.apply_watermark)
    applied_scn: int = 0
    leader_id: int | None = None
    lease_until: float = 0.0
    next_election_at: float = 0.0
    next_heartbeat_at: float = 0.0
    _match_lsn: dict[int, int] = field(default_factory=dict)
    _next_lsn: dict[int, int] = field(default_factory=dict)
    _votes: set[int] = field(default_factory=set)
    _scn: int = 0
    _term_start_lsn: int = 0
    _last_ack: dict[int, float] = field(default_factory=dict)
    # wait-event bookkeeping (virtual-clock timestamps): submit->commit
    # per lsn, append-send->ack per peer (both leader-side)
    _submit_at: dict[int, float] = field(default_factory=dict)
    _sent_at: dict[int, float] = field(default_factory=dict)
    # trace context captured at submit_log, so the commit advance can emit
    # a retrospective "palf replication" span into the submitting
    # statement's trace tree (full-link tracing across the bus)
    _submit_ctx: dict[int, Any] = field(default_factory=dict)

    def __post_init__(self):
        # constructor-provided membership = the config floor a truncation
        # can fall back to when every in-log config entry is cut away
        self._base_config = list(self.peers)
        if self.store is not None:
            entries, base, term, voted_for = self.store.load()
            if entries or term:
                self.log = LogView(
                    base, entries, self.store.base_prev_term
                )
                self.term = term
                self.voted_for = voted_for
                if entries:
                    self._scn = entries[-1].scn
                # re-adopt the newest membership recorded in the log
                for e in reversed(entries):
                    cfg = _decode_config(e.payload)
                    if cfg is not None:
                        self.peers = list(cfg)
                        break
        self.bus.register(self.node_id, self._on_message)
        self.next_election_at = (
            self.bus.now + LEASE_TIMEOUT + self._jitter()
        )

    # ------------------------------------------------------- durability
    def _persist_meta(self) -> None:
        if self.store is not None:
            self.store.save_meta(self.term, self.voted_for)

    def _persist_append(self, entries) -> None:
        if self.store is not None:
            self.store.append(entries)

    def _persist_sync(self) -> None:
        """Group-commit: durable point before ack/self-count."""
        if self.store is not None:
            self.store.sync()

    def recycle(self, upto_lsn: int) -> None:
        """Advance the disk recycle point (everything below upto_lsn is
        covered by a durable checkpoint). In-memory entries are trimmed
        too — a follower that has fallen below this point needs a
        snapshot-based rebuild, not log catch-up."""
        upto = min(upto_lsn, self.commit_lsn + 1)
        if upto <= self.log.base:
            return
        if self.store is not None:
            # disk recycling is segment-aligned and records its own base
            # info (the durable base differs from the in-memory one)
            self.store.recycle(upto)
        keep_term = self.log[upto - 1].term
        self.log = LogView(
            upto, self.log.entries[upto - self.log.base :], keep_term
        )

    # ------------------------------------------------------------ utils
    def _jitter(self) -> float:
        # deterministic per (node, term) spread so elections don't collide
        return ELECTION_JITTER * (1 + ((self.node_id * 2654435761 + self.term) % 97) / 97)

    def _majority(self) -> int:
        return len(self.peers) // 2 + 1

    def _last(self) -> tuple[int, int]:
        if len(self.log) == 0:
            return -1, 0
        if not self.log.entries:
            # fully-recycled log: the last entry's identity survives as the
            # recorded base info (elections must keep working post-recycle)
            return self.log.base - 1, self.log.base_prev_term
        e = self.log[-1]
        return e.lsn, e.term

    def quorum_alive_hint(self) -> bool:
        return self.role is Role.LEADER

    # -------------------------------------------------------- public API
    def submit_log(self, payload: bytes, scn: int | None = None) -> int | None:
        """Leader appends; returns lsn or None if not leader (caller retries
        at the real leader — the analog of OB_NOT_MASTER). Errsim:
        EN_LOG_SUBMIT injects append failures."""
        from ..share.errsim import errsim_point

        errsim_point("EN_LOG_SUBMIT")
        if self.role is not Role.LEADER:
            return None
        lsn = len(self.log)
        self._scn = max(self._scn + 1, scn or 0)
        e = LogEntry(lsn, self.term, self._scn, payload)
        m = getattr(self.bus, "metrics", None)
        tr = getattr(self.bus, "tracer", None)
        if tr is not None:
            ctx = tr.current_ctx()
            if ctx is not None:
                self._submit_at.setdefault(lsn, self.bus.now)
                self._submit_ctx[lsn] = ctx
        self.log.append(e)
        if m is not None:
            # "palf append": the leader's local durability window; "palf
            # commit" (recorded on commit advance) measures the
            # replication round on the bus's virtual clock
            self._submit_at[lsn] = self.bus.now
            m.add("palf log entries submitted")
            with m.waiting("palf append"):
                self._persist_append((e,))
                self._persist_sync()
        else:
            self._persist_append((e,))
            self._persist_sync()  # durable before counting self in the quorum
        self._advance_commit()  # single-replica groups commit immediately
        return lsn

    def tick(self) -> None:
        """Advance timers against the bus's virtual clock."""
        now = self.bus.now
        if self.role is Role.LEADER:
            # leader lease self-check: without acks from a majority within
            # the lease window, step down (the failure-detector demotion —
            # a partitioned/dead-network leader must not keep serving)
            alive = 1 + sum(
                1 for p, t in self._last_ack.items() if now - t < LEASE_TIMEOUT
            )
            if alive < self._majority():
                self._step_down(self.term, None)
                return
            if now >= self.next_heartbeat_at:
                self._broadcast_appends()
                self.next_heartbeat_at = now + HEARTBEAT_IVL
        else:
            lease_live = now < self.lease_until
            if not lease_live and now >= self.next_election_at:
                self._start_election()

    # ---------------------------------------------------------- election
    def _start_election(self, force: bool = False) -> None:
        self.role = Role.CANDIDATE
        self.term += 1
        self.voted_for = self.node_id
        self._persist_meta()  # durable before soliciting votes
        self._votes = {self.node_id}
        self.leader_id = None
        last_lsn, last_term = self._last()
        for p in self.peers:
            if p != self.node_id:
                self.bus.send(
                    self.node_id, p,
                    VoteReq(self.term, self.node_id, last_lsn, last_term, force),
                )
        self.next_election_at = self.bus.now + LEASE_TIMEOUT + self._jitter()
        if len(self.peers) == 1:
            self._become_leader()

    def submit_config(self, new_peers: list[int]) -> int | None:
        """Single-member-change membership update (LogConfigMgr analog):
        the leader logs the new member list and adopts it immediately
        (Raft: a config is effective once appended); followers adopt on
        append. Safe for one add OR one remove at a time — the migration
        path drives each change to commit before the next."""
        if self.role is not Role.LEADER:
            return None
        cur, new = set(self.peers), set(new_peers)
        if len(cur.symmetric_difference(new)) > 1:
            raise ValueError("one membership change at a time")
        lsn = self.submit_log(_encode_config(list(new_peers)))
        if lsn is not None:
            self._adopt_config(list(new_peers))
        return lsn

    def _readopt_config_from_log(self) -> None:
        """Adopt the newest config still in the log, else the base config
        the replica was constructed with (post-truncation recovery)."""
        for e in reversed(self.log.entries):
            cfg = _decode_config(e.payload)
            if cfg is not None:
                self._adopt_config(cfg)
                return
        self._adopt_config(list(self._base_config))

    def _adopt_config(self, new_peers: list[int]) -> None:
        self.peers = list(new_peers)
        if self.role is Role.LEADER:
            nxt = len(self.log)
            for p in self.peers:
                if p != self.node_id:
                    self._next_lsn.setdefault(p, nxt)
                    self._match_lsn.setdefault(p, -1)
                    self._last_ack.setdefault(p, self.bus.now)
            for m in (self._next_lsn, self._match_lsn, self._last_ack):
                for p in list(m):
                    if p not in self.peers:
                        del m[p]

    def transfer_leader(self, target: int) -> bool:
        """Hand leadership to `target` (must be caught up). Returns False if
        not leader or target is behind — caller keeps driving and retries."""
        if self.role is not Role.LEADER or target == self.node_id:
            return False
        if self._match_lsn.get(target, -1) != len(self.log) - 1:
            self._send_append_to(target)  # catch it up first
            return False
        self.bus.send(self.node_id, target, TimeoutNow(self.term))
        return True

    def _become_leader(self) -> None:
        self.role = Role.LEADER
        self.leader_id = self.node_id
        nxt = len(self.log)
        self._next_lsn = {p: nxt for p in self.peers if p != self.node_id}
        self._match_lsn = {p: -1 for p in self.peers if p != self.node_id}
        self._last_ack = {p: self.bus.now for p in self.peers if p != self.node_id}
        # A leader may only count replicas for entries of its own term
        # (prior-term entries commit transitively), so append a no-op to
        # unblock commitment of everything inherited from old leaders.
        self._scn += 1
        self._term_start_lsn = len(self.log)
        e = LogEntry(len(self.log), self.term, self._scn, b"")
        self.log.append(e)
        self._persist_append((e,))
        self._persist_sync()
        self._advance_commit()  # single-replica groups commit immediately
        self.next_heartbeat_at = self.bus.now  # heartbeat immediately
        self.tick()

    @property
    def is_ready_leader(self) -> bool:
        """Leader that committed its own-term no-op AND applied everything —
        only then are reads served (a fresh leader must finish replaying
        inherited entries first; the reference's role-change protocol waits
        the same way before the new leader goes active)."""
        return (
            self.role is Role.LEADER
            and self.commit_lsn >= self._term_start_lsn
            and self.applied_lsn == self.commit_lsn
        )

    def reset_election_timer(self) -> None:
        """Rejoin grace: a replica coming back from a restart/partition
        waits one full lease window for an incumbent leader's heartbeat
        before campaigning. Without this its stale next_election_at fires
        immediately, the term bump NACKs the healthy leader's appends and
        deposes it (restart disruption — the problem pre-vote solves)."""
        self.next_election_at = self.bus.now + LEASE_TIMEOUT + self._jitter()

    def _step_down(self, term: int, leader: int | None) -> None:
        self.role = Role.FOLLOWER
        if term > self.term:
            self.term = term
            self.voted_for = None
            self._persist_meta()  # durable before acting in the new term
        if leader is not None:
            self.leader_id = leader
        self.next_election_at = self.bus.now + LEASE_TIMEOUT + self._jitter()

    # ------------------------------------------------------- replication
    def _broadcast_appends(self) -> None:
        for p in self.peers:
            if p != self.node_id:
                self._send_append_to(p)

    def _advance_commit(self) -> None:
        # highest lsn replicated on a majority AND from the current term
        floor = max(self.commit_lsn, self.log.base - 1)
        prev_commit = self.commit_lsn
        for lsn in range(len(self.log) - 1, floor, -1):
            if self.log[lsn].term != self.term:
                break
            acked = 1 + sum(1 for m in self._match_lsn.values() if m >= lsn)
            if acked >= self._majority():
                self.commit_lsn = lsn
                break
        if self.commit_lsn > prev_commit and (self._submit_at or self._submit_ctx):
            m = getattr(self.bus, "metrics", None)
            tr = getattr(self.bus, "tracer", None)
            for lsn in range(prev_commit + 1, self.commit_lsn + 1):
                t = self._submit_at.pop(lsn, None)
                if t is not None and m is not None:
                    m.wait("palf commit", self.bus.now - t)
                ctx = self._submit_ctx.pop(lsn, None)
                if ctx is not None and tr is not None and t is not None:
                    # retrospective span: the whole replication round for
                    # this lsn (submit -> majority ack) on the virtual clock
                    tr.record_span(
                        "palf replication", ctx, t, self.bus.now,
                        node=self.node_id, lsn=lsn,
                    )
        self._apply()

    def _apply(self) -> None:
        while self.applied_lsn < self.commit_lsn:
            self.applied_lsn += 1
            e = self.log[self.applied_lsn]
            self.applied_scn = max(self.applied_scn, e.scn)
            # membership entries are consensus-internal: never surfaced
            # to the state machine
            if e.payload.startswith(CONFIG_PREFIX):
                continue
            if self.on_commit is not None:
                self.on_commit(e)

    # ------------------------------------------------------ msg handling
    def _on_message(self, src: int, msg: Any) -> None:
        if isinstance(msg, AppendReq):
            self._on_append(src, msg)
        elif isinstance(msg, AppendAck):
            self._on_append_ack(src, msg)
        elif isinstance(msg, VoteReq):
            self._on_vote_req(src, msg)
        elif isinstance(msg, VoteResp):
            self._on_vote_resp(src, msg)
        elif isinstance(msg, TimeoutNow):
            if msg.term == self.term and self.role is not Role.LEADER:
                self._start_election(force=True)

    def _on_append(self, src: int, m: AppendReq) -> None:
        if m.term < self.term:
            self.bus.send(self.node_id, src, AppendAck(self.term, -1, False))
            return
        # valid leader for this term: refresh lease
        self._step_down(m.term, m.leader_id)
        self.lease_until = self.bus.now + LEASE_TIMEOUT
        # log matching; prev below base = recycled = committed = matched
        if m.prev_lsn >= self.log.base:
            if m.prev_lsn >= len(self.log) or self.log[m.prev_lsn].term != m.prev_term:
                self.bus.send(self.node_id, src, AppendAck(self.term, -1, False))
                return
        # append, truncating any conflicting suffix
        appended = []
        for e in m.entries:
            if e.lsn < self.log.base:
                continue  # below our checkpointed prefix: already committed
            if e.lsn < len(self.log):
                if self.log[e.lsn].term != e.term:
                    if e.lsn <= self.commit_lsn:
                        raise AssertionError(
                            f"node {self.node_id}: conflicting entry at committed lsn {e.lsn}"
                        )
                    had_config = any(
                        en.payload.startswith(CONFIG_PREFIX)
                        for en in self.log.entries[e.lsn - self.log.base:]
                    )
                    del self.log[e.lsn :]
                    if self.store is not None:
                        self.store.truncate_from(e.lsn)
                    if had_config:
                        # an adopted-but-uncommitted membership was cut:
                        # fall back to the newest surviving config
                        self._readopt_config_from_log()
                    appended = [a for a in appended if a.lsn < e.lsn]
                    self.log.append(e)
                    appended.append(e)
                # else: duplicate, keep
            else:
                self.log.append(e)
                appended.append(e)
        mx = getattr(self.bus, "metrics", None)
        if mx is not None and appended:
            mx.add("palf log entries replicated", len(appended))
        if appended:
            tr = getattr(self.bus, "tracer", None)
            ctx = self.bus.delivery_ctx() if hasattr(self.bus, "delivery_ctx") else None
            if tr is not None and ctx is not None:
                # follower-side durability work, tagged with THIS node so
                # SHOW TRACE shows which replica appended for the statement
                tr.record_span(
                    "palf append", ctx, self.bus.now, self.bus.now,
                    node=self.node_id, entries=len(appended),
                )
            self._persist_append(appended)
            # adopt any membership change in the appended suffix (config
            # is effective at append; the newest one wins)
            for e in appended:
                cfg = _decode_config(e.payload)
                if cfg is not None:
                    self._adopt_config(cfg)
        self._persist_sync()  # durable BEFORE the ack joins a commit quorum
        new_commit = min(m.commit_lsn, len(self.log) - 1)
        if new_commit > self.commit_lsn:
            self.commit_lsn = new_commit
        self._apply()
        ack_lsn = m.prev_lsn + len(m.entries)
        self.bus.send(self.node_id, src, AppendAck(self.term, ack_lsn, True))

    def _on_append_ack(self, src: int, m: AppendAck) -> None:
        if self.role is not Role.LEADER:
            return
        if m.term > self.term:
            self._step_down(m.term, None)
            return
        self._last_ack[src] = self.bus.now
        mx = getattr(self.bus, "metrics", None)
        sent = self._sent_at.pop(src, None)
        if mx is not None:
            mx.add("palf acks received")
            if sent is not None:
                mx.wait("palf ack", self.bus.now - sent)
        tr = getattr(self.bus, "tracer", None)
        ctx = self.bus.delivery_ctx() if hasattr(self.bus, "delivery_ctx") else None
        if tr is not None and ctx is not None and m.success:
            tr.record_span(
                "palf ack", ctx,
                sent if sent is not None else self.bus.now, self.bus.now,
                node=src, ack_lsn=m.ack_lsn,
            )
        if m.success:
            self._match_lsn[src] = max(self._match_lsn.get(src, -1), m.ack_lsn)
            self._next_lsn[src] = self._match_lsn[src] + 1
            self._advance_commit()
            if self._next_lsn[src] < len(self.log):
                # more to stream: push immediately instead of next heartbeat
                self._send_append_to(src)
        else:
            # back off one step and retry (log reconciliation)
            self._next_lsn[src] = max(0, self._next_lsn.get(src, len(self.log)) - 1)
            self._send_append_to(src)

    def _send_append_to(self, p: int) -> None:
        # a follower below our recycled base needs a snapshot rebuild, not
        # log catch-up — clamp to base (ha/rebuild drives the snapshot)
        nxt = max(self._next_lsn.get(p, len(self.log)), self.log.base)
        prev_lsn = nxt - 1
        if prev_lsn < 0:
            prev_term = 0
        elif prev_lsn < self.log.base:
            prev_term = self.log.base_prev_term
        else:
            prev_term = self.log[prev_lsn].term
        entries = tuple(self.log[nxt : nxt + MAX_INFLIGHT])
        # oldest outstanding send wins: the ack wait must cover the full
        # round, not reset on every heartbeat re-send
        self._sent_at.setdefault(p, self.bus.now)
        self.bus.send(
            self.node_id, p,
            AppendReq(self.term, self.node_id, prev_lsn, prev_term, entries, self.commit_lsn),
        )

    def _on_vote_req(self, src: int, m: VoteReq) -> None:
        if self.bus.now < self.lease_until and not m.force:
            # lease election: current leader still holds a live lease
            self.bus.send(self.node_id, src, VoteResp(self.term, False))
            return
        if m.term > self.term:
            # adopt the term, but do NOT let a denied candidate push our
            # election timer (only a GRANT defers us, below): a stale
            # rejoining candidate with deterministically-small jitter
            # would otherwise re-campaign ahead of every up-to-date
            # replica forever — a term-inflation livelock with no leader
            keep = self.next_election_at
            self._step_down(m.term, None)
            self.next_election_at = keep
        granted = False
        if m.term == self.term and self.voted_for in (None, m.candidate_id):
            last_lsn, last_term = self._last()
            up_to_date = (m.last_term, m.last_lsn) >= (last_term, last_lsn)
            if up_to_date:
                granted = True
                self.voted_for = m.candidate_id
                self._persist_meta()  # the vote must survive restart
                self.next_election_at = self.bus.now + LEASE_TIMEOUT + self._jitter()
        self.bus.send(self.node_id, src, VoteResp(self.term, granted))

    def _on_vote_resp(self, src: int, m: VoteResp) -> None:
        if self.role is not Role.CANDIDATE:
            return
        if m.term > self.term:
            self._step_down(m.term, None)
            return
        if m.granted and m.term == self.term:
            self._votes.add(src)
            if len(self._votes) >= self._majority():
                self._become_leader()


def run_until(bus: LocalBus, replicas: list[PalfReplica], cond, max_time: float = 30.0,
              dt: float = 0.01) -> bool:
    """Drive ticks + delivery until cond() or timeout. Test harness helper."""
    deadline = bus.now + max_time
    while bus.now < deadline:
        for r in replicas:
            r.tick()
        bus.advance(dt)
        if cond():
            return True
    return False


def leader_of(replicas: list[PalfReplica]) -> PalfReplica | None:
    leaders = [r for r in replicas if r.role is Role.LEADER]
    if not leaders:
        return None
    return max(leaders, key=lambda r: r.term)
