"""Log archive: continuous copy of committed palf entries to durable files.

Reference surface: logservice/archiveservice — per-LS continuous archive of
palf logs to object storage in segment files, with a persisted progress
point so archiving resumes where it stopped; consumed by restore
(logservice/restoreservice) and PITR.

Segment format: fixed header per entry
  <q lsn> <q term> <q scn> <I payload_len> <I crc32(payload)> payload
Progress file holds the next LSN to archive. Segments rotate by entry
count so restores can skip ahead cheaply.
"""

from __future__ import annotations

import os
import struct
import zlib

_ENTRY = struct.Struct("<qqqII")
SEGMENT_ENTRIES = 4096


class ArchiveWriter:
    def __init__(self, root: str, ls_id: int):
        self.dir = os.path.join(root, f"ls_{ls_id}")
        os.makedirs(self.dir, exist_ok=True)
        self._progress_path = os.path.join(self.dir, "progress")
        self.next_lsn = 0
        if os.path.exists(self._progress_path):
            with open(self._progress_path) as f:
                self.next_lsn = int(f.read().strip() or 0)
        self._recover()

    def _recover(self) -> None:
        """Crash recovery: entries may have been appended after the last
        progress write; scan the TAIL segment (bounded work) and resume
        past whatever is actually on disk, so resume never duplicates."""
        segs = sorted(
            f for f in os.listdir(self.dir) if f.endswith(".alog")
        )
        if not segs:
            return
        last = os.path.join(self.dir, segs[-1])
        with open(last, "rb") as f:
            buf = f.read()
        # same record layout as the palf LogStore: reuse ITS crash-boundary
        # scanner rather than a drifting copy of the loop
        from .store import scan_records

        recs, good = scan_records(buf)
        max_lsn = max((r[0] for r in recs), default=-1)
        if good < len(buf):
            # torn final record (crash mid-append): truncate to the last
            # whole-entry boundary so resumed appends don't bury partial
            # bytes inside the segment (which would corrupt every later read)
            with open(last, "r+b") as f:
                f.truncate(good)
        self.next_lsn = max(self.next_lsn, max_lsn + 1)

    def _segment_path(self, lsn: int) -> str:
        return os.path.join(self.dir, f"seg_{lsn // SEGMENT_ENTRIES:08d}.alog")

    def archive_from(self, palf) -> int:
        """Archive newly COMMITTED entries from a palf replica; returns the
        number archived. Only the committed prefix is durable truth —
        uncommitted tail entries may be rewritten by a new leader."""
        hi = palf.commit_lsn
        n = 0
        while self.next_lsn <= hi:
            e = palf.log[self.next_lsn]
            rec = _ENTRY.pack(
                e.lsn, e.term, e.scn, len(e.payload),
                zlib.crc32(e.payload) & 0xFFFFFFFF,
            ) + e.payload
            with open(self._segment_path(e.lsn), "ab") as f:
                f.write(rec)
            self.next_lsn += 1
            n += 1
        if n:
            tmp = self._progress_path + ".tmp"
            with open(tmp, "w") as f:
                f.write(str(self.next_lsn))
            os.replace(tmp, self._progress_path)
        return n


class ArchiveReader:
    def __init__(self, root: str, ls_id: int):
        self.dir = os.path.join(root, f"ls_{ls_id}")

    def entries(self, from_lsn: int = 0, to_scn: int | None = None):
        """Yield (lsn, term, scn, payload) in LSN order."""
        if not os.path.isdir(self.dir):
            return
        segs = sorted(
            f for f in os.listdir(self.dir) if f.endswith(".alog")
        )
        # whole-segment skip: seg_<i> holds LSNs [i*SEGMENT_ENTRIES, ...)
        first_seg = from_lsn // SEGMENT_ENTRIES
        segs = [
            s for s in segs
            if int(s[len("seg_"):-len(".alog")]) >= first_seg
        ]
        for seg in segs:
            with open(os.path.join(self.dir, seg), "rb") as f:
                buf = f.read()
            pos = 0
            while pos + _ENTRY.size <= len(buf):
                lsn, term, scn, plen, crc = _ENTRY.unpack_from(buf, pos)
                pos += _ENTRY.size
                payload = buf[pos : pos + plen]
                pos += plen
                if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
                    raise IOError(f"archive corruption at lsn {lsn} in {seg}")
                if lsn < from_lsn:
                    continue
                if to_scn is not None and scn > to_scn:
                    return
                yield lsn, term, scn, payload
