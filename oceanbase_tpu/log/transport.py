"""Message transport for replication: in-process bus with fault injection.

Reference surface: the RPC plane PALF rides on — obrpc typed async proxies
(deps/oblib/src/rpc/obrpc) and LogNetService push/ack/fetch
(logservice/palf/log_net_service.h:38) — and the ERRSIM tracepoint style of
fault injection (deps/oblib/src/lib/utility/ob_tracepoint_def.h).

The rebuild separates the consensus state machine from time and wires: the
LocalBus delivers messages between in-process replicas under an explicit
virtual clock, with programmable drop/delay/partition faults. This makes the
3-replica tests deterministic (no sleeps, no flakes) — the same pattern the
reference gets from forking three observers (mittest/multi_replica) but
simulable. A TCP transport with the same interface slots in for real
multi-process deployment (cluster services layer).
"""

from __future__ import annotations

import random
import threading
from collections import defaultdict
from dataclasses import dataclass, field
from typing import Any, Callable


@dataclass
class Envelope:
    src: int
    dst: int
    msg: Any
    deliver_at: float
    # full-link trace context: (trace_id, parent_span_id) of the statement
    # that caused this message, or None for autonomous traffic (ticks,
    # elections). Carried across hops so replica-side work lands in the
    # originating statement's span tree (ObTrace's flt_trace_id analog).
    trace_ctx: Any = None


@dataclass
class LocalBus:
    """Deterministic in-process message bus with a virtual clock."""

    now: float = 0.0
    latency: float = 0.001
    drop_prob: float = 0.0
    seed: int = 0
    _queue: list[Envelope] = field(default_factory=list)
    _handlers: dict[int, Callable[[int, Any], None]] = field(default_factory=dict)
    _partitions: set[frozenset] = field(default_factory=set)
    _down: set[int] = field(default_factory=set)
    _rng: random.Random = None  # type: ignore[assignment]
    # tenant metrics registry (share/metrics.MetricsRegistry); when wired,
    # sent/dropped/delivered surface in __all_virtual_sysstat as
    # "rpc packets ..." instead of living only in the private dict below
    metrics: Any = None
    # tenant tracer (server/diag.Tracer); when wired, send() stamps each
    # envelope with the sender's current trace context and advance() makes
    # it visible to handlers via delivery_ctx(), so replies sent while
    # handling a delivery inherit the originating statement's trace
    tracer: Any = None
    stats: dict[str, int] = field(default_factory=lambda: defaultdict(int))
    _delivery_ctx: Any = field(default=None, repr=False)
    # serializes clock advancement and queue mutation: multiple serving
    # sessions retry statements concurrently and each retry path may drive
    # the cluster (settle/leader_node). Reentrant because handlers called
    # under advance() send replies through the same bus.
    drive_lock: threading.RLock = field(
        default_factory=threading.RLock, repr=False)

    def __post_init__(self):
        self._rng = random.Random(self.seed)

    def delivery_ctx(self) -> Any:
        """Trace context of the envelope currently being delivered (only
        meaningful inside a handler called from advance())."""
        return self._delivery_ctx

    def _current_ctx(self) -> Any:
        if self.tracer is not None:
            ctx = self.tracer.current_ctx()
            if ctx is not None:
                return ctx
        return self._delivery_ctx

    def _bump(self, key: str, n: int = 1) -> None:
        self.stats[key] += n
        if self.metrics is not None:
            self.metrics.add(f"rpc packets {key}", n)

    def register(self, node_id: int, handler: Callable[[int, Any], None]) -> None:
        self._handlers[node_id] = handler

    # ------------------------------------------------------------ faults
    def partition(self, group_a: set[int], group_b: set[int]) -> None:
        for a in group_a:
            for b in group_b:
                self._partitions.add(frozenset((a, b)))

    def heal(self) -> None:
        self._partitions.clear()

    def kill(self, node_id: int) -> None:
        self._down.add(node_id)

    def revive(self, node_id: int) -> None:
        self._down.discard(node_id)

    def _blocked(self, a: int, b: int) -> bool:
        return (
            a in self._down
            or b in self._down
            or frozenset((a, b)) in self._partitions
        )

    # ---------------------------------------------------------- delivery
    def send(self, src: int, dst: int, msg: Any) -> None:
        with self.drive_lock:
            self._bump("sent")
            if self._blocked(src, dst):
                self._bump("dropped")
                return
            if self.drop_prob and self._rng.random() < self.drop_prob:
                self._bump("dropped")
                return
            self._queue.append(
                Envelope(src, dst, msg, self.now + self.latency,
                         trace_ctx=self._current_ctx())
            )

    def advance(self, dt: float) -> int:
        """Advance virtual time, delivering everything due. Returns count."""
        with self.drive_lock:
            self.now += dt
            delivered = 0
            while True:
                due = [e for e in self._queue if e.deliver_at <= self.now]
                if not due:
                    break
                self._queue = [
                    e for e in self._queue if e.deliver_at > self.now
                ]
                due.sort(key=lambda e: e.deliver_at)
                for e in due:
                    if self._blocked(e.src, e.dst):
                        self._bump("dropped")
                        continue
                    h = self._handlers.get(e.dst)
                    if h is not None:
                        self._delivery_ctx = e.trace_ctx
                        try:
                            h(e.src, e.msg)
                        finally:
                            self._delivery_ctx = None
                        delivered += 1
            self._bump("delivered", delivered)
            return delivered
