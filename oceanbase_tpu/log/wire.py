"""Typed, versioned wire codec for the inter-node bus.

Reference surface: obrpc packet framing + pcode-dispatched typed payloads
(deps/oblib/src/rpc/obrpc/ob_rpc_packet_list.h — 1089 pcodes;
ob_rpc_proxy_macros.h — macro-generated typed proxies). The rebuild's
control plane is small, so the codec is hand-rolled: one tag byte per
message type ("pcode"), fixed-width little-endian fields, length-prefixed
bytes. No pickle anywhere: a malformed or adversarial frame can at worst
fail to decode (DecodeError) — it cannot execute code.

Framing (tcp_transport.py): every frame is
    magic u16 | version u8 | kind u8 | dst u32 | len u32 | payload
kind 0 = HELLO (payload = auth token), kind 1 = MSG (payload =
src u32 | tag u8 | body). Connections must HELLO first when the bus has
an auth token; frames before a valid HELLO are rejected and the
connection dropped.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

MAGIC = 0x0BA5
VERSION = 1
FRAME = struct.Struct("<HBBII")  # magic, version, kind, dst, payload len
KIND_HELLO = 0
KIND_MSG = 1

_HDR = struct.Struct("<IB")  # src, tag


class DecodeError(Exception):
    pass


# ---- primitive packers -----------------------------------------------------

def _pb(out: list, b: bytes):
    out.append(struct.pack("<I", len(b)))
    out.append(b)


def _rb(buf: memoryview, off: int) -> tuple[bytes, int]:
    if off + 4 > len(buf):
        raise DecodeError("short bytes length")
    (n,) = struct.unpack_from("<I", buf, off)
    off += 4
    if off + n > len(buf):
        raise DecodeError("short bytes body")
    return bytes(buf[off:off + n]), off + n


# ---- message registry ------------------------------------------------------

_ENCODERS: dict[type, tuple[int, object]] = {}
_DECODERS: dict[int, object] = {}


def register(tag: int, cls, fmt: str, fields: tuple[str, ...],
             bytes_fields: tuple[str, ...] = ()):
    """Register a flat dataclass: `fmt` packs the non-bytes `fields` in
    order; `bytes_fields` follow as length-prefixed blobs."""
    st = struct.Struct(fmt)

    def enc(msg, out: list):
        out.append(st.pack(*[
            int(getattr(msg, f)) if not isinstance(getattr(msg, f), float)
            else getattr(msg, f)
            for f in fields
        ]))
        for f in bytes_fields:
            _pb(out, getattr(msg, f))

    def dec(buf: memoryview, off: int):
        if off + st.size > len(buf):
            raise DecodeError(f"short {cls.__name__}")
        vals = st.unpack_from(buf, off)
        off += st.size
        kw = dict(zip(fields, vals))
        for f in bytes_fields:
            kw[f], off = _rb(buf, off)
        return cls(**_coerce(cls, kw)), off

    _ENCODERS[cls] = (tag, enc)
    _DECODERS[tag] = dec
    return cls


def _coerce(cls, kw):
    # struct returns ints; dataclasses with bool fields need real bools
    hints = getattr(cls, "__annotations__", {})
    for k, t in hints.items():
        if k in kw and t in ("bool", bool):
            kw[k] = bool(kw[k])
    return kw


# palf messages --------------------------------------------------------------

from .palf import (  # noqa: E402 — registry must see the classes
    AppendAck,
    AppendReq,
    LogEntry,
    TimeoutNow,
    VoteReq,
    VoteResp,
)

_ENTRY = struct.Struct("<qqq")  # lsn, term, scn (+ payload bytes)


def _enc_append_req(msg: AppendReq, out: list):
    out.append(struct.pack(
        "<qiqqqI", msg.term, msg.leader_id, msg.prev_lsn, msg.prev_term,
        msg.commit_lsn, len(msg.entries),
    ))
    for e in msg.entries:
        out.append(_ENTRY.pack(e.lsn, e.term, e.scn))
        _pb(out, e.payload)


def _dec_append_req(buf: memoryview, off: int):
    st = struct.Struct("<qiqqqI")
    if off + st.size > len(buf):
        raise DecodeError("short AppendReq")
    term, leader, prev_lsn, prev_term, commit, n = st.unpack_from(buf, off)
    off += st.size
    if n > 1 << 22:
        raise DecodeError("absurd entry count")
    entries = []
    for _ in range(n):
        if off + _ENTRY.size > len(buf):
            raise DecodeError("short LogEntry")
        lsn, eterm, scn = _ENTRY.unpack_from(buf, off)
        off += _ENTRY.size
        payload, off = _rb(buf, off)
        entries.append(LogEntry(lsn, eterm, scn, payload))
    return AppendReq(
        term, leader, prev_lsn, prev_term, tuple(entries), commit
    ), off


_ENCODERS[AppendReq] = (1, _enc_append_req)
_DECODERS[1] = _dec_append_req

register(2, AppendAck, "<qqB", ("term", "ack_lsn", "success"))
register(3, VoteReq, "<qiqqB",
         ("term", "candidate_id", "last_lsn", "last_term", "force"))
register(4, VoteResp, "<qB", ("term", "granted"))
register(5, TimeoutNow, "<q", ("term",))

# keepalive ------------------------------------------------------------------

from ..ha.detect import _Ping, _Pong  # noqa: E402

register(6, _Ping, "<d", ("t",))
register(7, _Pong, "<d", ("t",))

# distributed deadlock probes ------------------------------------------------

from ..share.deadlock import AbortGrant, ConfirmRequest, LockProbe  # noqa: E402

register(8, LockProbe, "<qqqBq",
         ("initiator", "holder", "max_seen", "hops", "init_token"))
register(9, ConfirmRequest, "<qqqi",
         ("initiator", "victim", "init_token", "victim_node"))
register(10, AbortGrant, "<qq", ("initiator", "victim"))


# ---- top level -------------------------------------------------------------

def encode_msg(src: int, msg) -> bytes:
    try:
        tag, enc = _ENCODERS[type(msg)]
    except KeyError:
        raise TypeError(
            f"unregistered bus message type {type(msg).__name__}; add it "
            f"to log/wire.py's registry"
        ) from None
    out: list[bytes] = [_HDR.pack(src, tag)]
    enc(msg, out)
    return b"".join(out)


def decode_msg(payload: bytes) -> tuple[int, object]:
    buf = memoryview(payload)
    if len(buf) < _HDR.size:
        raise DecodeError("short header")
    src, tag = _HDR.unpack_from(buf, 0)
    dec = _DECODERS.get(tag)
    if dec is None:
        raise DecodeError(f"unknown tag {tag}")
    msg, off = dec(buf, _HDR.size)
    if off != len(buf):
        raise DecodeError("trailing bytes")
    return src, msg
