"""Replicated log service (PALF-lite) + transports.

Layer map (SURVEY.md §2.4 -> rebuild):
  transport.py  message bus w/ virtual clock + fault injection (obrpc analog)
  palf.py       leader-based replicated log: sliding window, majority commit,
                lease election, log reconciliation
"""

from .palf import (
    AppendAck,
    AppendReq,
    LogEntry,
    PalfReplica,
    Role,
    VoteReq,
    VoteResp,
    leader_of,
    run_until,
)
from .transport import LocalBus

__all__ = [
    "LocalBus",
    "LogEntry",
    "PalfReplica",
    "Role",
    "AppendReq",
    "AppendAck",
    "VoteReq",
    "VoteResp",
    "run_until",
    "leader_of",
]
