"""Replicated log service (PALF-lite) + transports.

Layer map (SURVEY.md §2.4 -> rebuild):
  transport.py  message bus w/ virtual clock + fault injection (obrpc analog)
  palf.py       leader-based replicated log: sliding window, majority commit,
                lease election, log reconciliation
"""

from .palf import (
    AppendAck,
    AppendReq,
    LogEntry,
    LogView,
    PalfReplica,
    Role,
    VoteReq,
    VoteResp,
    leader_of,
    run_until,
)
from .store import LogStore
from .transport import LocalBus

__all__ = [
    "LocalBus",
    "LogEntry",
    "LogView",
    "LogStore",
    "PalfReplica",
    "Role",
    "AppendReq",
    "AppendAck",
    "VoteReq",
    "VoteResp",
    "run_until",
    "leader_of",
]
