"""TCP transport: real-time multi-process message bus for replication.

Reference surface: the real deployment plane the LocalBus simulates —
obrpc over pkt-nio sockets (deps/oblib/src/rpc). The reference tests true
multi-node behavior by forking three observer processes as three zones
(mittest/multi_replica/env/ob_multi_replica_test_base.cpp:472); the
rebuild's TcpBus lets the SAME PalfReplica state machine run across real
processes: it exposes the LocalBus surface palf uses (`now`, `send`,
`register`).

Frames ride the typed, versioned codec in log/wire.py (tagged binary
messages — no pickle, a hostile frame cannot execute code), and every
connection must present the cluster auth token in a HELLO frame before
any message is accepted.
"""

from __future__ import annotations

import hmac
import socket
import threading
import time

from .wire import (
    FRAME,
    KIND_HELLO,
    KIND_MSG,
    MAGIC,
    VERSION,
    DecodeError,
    decode_msg,
    encode_msg,
)


class TcpBus:
    """One process's endpoint. `route` maps every node id to the
    (host, port) of the process hosting it; ids listed in `local_nodes`
    are served by this process. `auth_token` (bytes) gates inbound
    connections: peers must HELLO with the same token first."""

    def __init__(self, listen_port: int, route: dict[int, tuple[str, int]],
                 local_nodes: set[int] | None = None,
                 auth_token: bytes = b"",
                 tls: tuple | None = None):
        self.listen_port = listen_port
        self.route = route
        self.local_nodes = set(local_nodes or ())
        self.auth_token = auth_token
        # (server ssl.SSLContext, client ssl.SSLContext) — mutual-TLS
        # upgrade of every bus connection (share/tls.py; the reference's
        # ussl-hook interception point). None = plaintext (tests, single
        # host). With TLS on, the HELLO token is no longer observable on
        # the wire, closing its replay window.
        self.tls = tls
        self._handlers: dict[int, object] = {}
        self._conns: dict[tuple[str, int], socket.socket] = {}
        self._t0 = time.monotonic()
        # _lock guards only the _conns map; per-destination locks serialize
        # connect/sendall, so one dead peer's 1s connect timeout cannot
        # stall sends (palf heartbeats, votes) to healthy peers
        self._lock = threading.Lock()
        self._dst_locks: dict[tuple[str, int], threading.Lock] = {}
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._listener: socket.socket | None = None
        self.rejected_frames = 0  # malformed / unauthenticated (observability)

    @property
    def now(self) -> float:
        return time.monotonic() - self._t0

    def register(self, node_id: int, handler) -> None:
        self._handlers[node_id] = handler
        self.local_nodes.add(node_id)

    # ---------------------------------------------------------- sending
    @staticmethod
    def _frame(kind: int, dst: int, payload: bytes) -> bytes:
        return FRAME.pack(MAGIC, VERSION, kind, dst, len(payload)) + payload

    def send(self, src: int, dst: int, msg) -> None:
        if dst in self.local_nodes:
            h = self._handlers.get(dst)
            if h is not None:
                h(src, msg)
            return
        addr = self.route.get(dst)
        if addr is None:
            return
        frame = self._frame(KIND_MSG, dst, encode_msg(src, msg))
        with self._lock:
            dlock = self._dst_locks.setdefault(addr, threading.Lock())
        try:
            with dlock:
                with self._lock:
                    conn = self._conns.get(addr)
                if conn is None:
                    conn = socket.create_connection(addr, timeout=1.0)
                    conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                    if self.tls is not None:
                        conn = self.tls[1].wrap_socket(conn)
                    # authenticate the connection before the first message
                    conn.sendall(
                        self._frame(KIND_HELLO, 0, self.auth_token)
                    )
                    with self._lock:
                        self._conns[addr] = conn
                conn.sendall(frame)
        except OSError:
            # network semantics: drops are normal; consensus retries
            with self._lock:
                c = self._conns.pop(addr, None)
            if c is not None:
                try:
                    c.close()
                except OSError:
                    pass

    # --------------------------------------------------------- receiving
    def start(self) -> None:
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("127.0.0.1", self.listen_port))
        self._listener.listen(16)
        self._listener.settimeout(0.2)

        def accept_loop():
            while not self._stop.is_set():
                try:
                    conn, _ = self._listener.accept()
                except socket.timeout:
                    continue
                except OSError:
                    return
                t = threading.Thread(
                    target=self._reader, args=(conn,), daemon=True
                )
                t.start()
                self._threads.append(t)

        t = threading.Thread(target=accept_loop, daemon=True)
        t.start()
        self._threads.append(t)

    def _reader(self, conn: socket.socket) -> None:
        if self.tls is not None:
            try:
                conn.settimeout(5.0)
                conn = self.tls[0].wrap_socket(conn, server_side=True)
            except (OSError, ValueError):
                self.rejected_frames += 1
                try:
                    conn.close()
                except OSError:
                    pass
                return
        conn.settimeout(0.5)
        buf = b""
        authed = not self.auth_token
        drop = False
        while not self._stop.is_set() and not drop:
            try:
                chunk = conn.recv(65536)
            except socket.timeout:
                continue
            except OSError:
                break
            if not chunk:
                break
            buf += chunk
            while len(buf) >= FRAME.size:
                magic, ver, kind, dst, plen = FRAME.unpack_from(buf)
                if magic != MAGIC or ver != VERSION or plen > (64 << 20):
                    self.rejected_frames += 1
                    drop = True  # unframed garbage: drop the connection
                    break
                if len(buf) < FRAME.size + plen:
                    break
                payload = buf[FRAME.size:FRAME.size + plen]
                buf = buf[FRAME.size + plen:]
                if kind == KIND_HELLO:
                    if hmac.compare_digest(payload, self.auth_token):
                        authed = True
                    else:
                        self.rejected_frames += 1
                        drop = True
                        break
                    continue
                if not authed:
                    self.rejected_frames += 1
                    drop = True  # message before a valid HELLO
                    break
                try:
                    src, msg = decode_msg(payload)
                except (DecodeError, TypeError):
                    self.rejected_frames += 1
                    continue  # typed decode failed: drop the frame
                h = self._handlers.get(dst)
                if h is not None:
                    h(src, msg)
        try:
            conn.close()
        except OSError:
            pass

    def stop(self) -> None:
        self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        with self._lock:
            for c in self._conns.values():
                try:
                    c.close()
                except OSError:
                    pass
            self._conns.clear()
