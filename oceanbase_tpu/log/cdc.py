"""CDC: change-data-capture over the replicated log.

Reference surface: logservice/libobcdc — the CDC client fetches palf logs,
reassembles transactions from redo/prepare/commit records, and emits
ordered row messages to downstream consumers (binlog-style).

The rebuild's CdcClient tails either a live palf replica or an
ArchiveReader, parses TxRecords, and assembles:

  REDO_COMMIT           -> one-phase tx: emit immediately
  PREPARE               -> stash this participant's redo
  COMMIT                -> emit stashed redo with the final commit version
  ABORT                 -> drop stashed redo (aborted txs never surface)

Events carry (tx_id, commit_version, row ops). Within one LS the emission
order is the log (= apply) order; cross-LS consumers merge by
commit_version like the reference's sequencer.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..tx.records import RecordType, TxRecord


@dataclass(frozen=True)
class RowChange:
    tablet_id: int
    op: str  # "put" | "delete"
    key: tuple
    values: tuple | None


@dataclass(frozen=True)
class TxChange:
    tx_id: int
    commit_version: int
    ls_id: int
    rows: tuple[RowChange, ...]
    # (tablet_id, column, code, string): dictionary growth logged with the
    # tx, letting consumers decode VARCHAR codes without leader state
    dict_appends: tuple = ()
    # 2PC/XA: every participant LS (from the prepare record) — consumers
    # needing cross-LS atomicity (the standby) hold a tx until all
    # participants' streams emitted it
    participants: tuple[int, ...] = ()


@dataclass
class CdcClient:
    """Tail one LS's log and emit committed transaction changes."""

    ls_id: int
    next_lsn: int = 0
    _pending: dict[int, tuple] = field(default_factory=dict)  # tx -> (redo, dicts)

    def _events_from(self, records) -> list[TxChange]:
        out: list[TxChange] = []
        for rec in records:
            if rec.rtype is RecordType.REDO_COMMIT:
                out.append(self._tx_change(rec.tx_id, rec.commit_version,
                                           rec.mutations, rec.dict_appends))
            elif rec.rtype in (RecordType.PREPARE, RecordType.XA_PREPARE):
                # XA parks between prepare and the external decision but
                # the CDC contract is identical: redo surfaces only with
                # the COMMIT record's version
                self._pending[rec.tx_id] = (
                    rec.mutations, rec.dict_appends, rec.participants)
            elif rec.rtype is RecordType.COMMIT:
                muts, da, parts = self._pending.pop(
                    rec.tx_id, ((), (), ()))
                out.append(self._tx_change(rec.tx_id, rec.commit_version,
                                           muts, da, parts))
            elif rec.rtype is RecordType.ABORT:
                self._pending.pop(rec.tx_id, None)
        return out

    def _tx_change(self, tx_id, version, mutations, dict_appends,
                   participants=()) -> TxChange:
        rows = tuple(
            RowChange(m.tablet_id, "put" if m.op == 0 else "delete",
                      m.key, m.values)
            for m in mutations
        )
        return TxChange(tx_id, version, self.ls_id, rows,
                        tuple(dict_appends), tuple(participants))

    def poll_palf(self, palf) -> list[TxChange]:
        """Consume newly committed entries from a live replica."""
        recs = []
        while self.next_lsn <= palf.commit_lsn:
            payload = palf.log[self.next_lsn].payload
            self.next_lsn += 1
            if payload:
                recs.append(TxRecord.from_bytes(payload))
        return self._events_from(recs)

    def poll_archive(self, reader, to_scn: int | None = None) -> list[TxChange]:
        """Consume archived entries (restore/offline pipelines)."""
        recs = []
        for lsn, _term, _scn, payload in reader.entries(self.next_lsn, to_scn):
            self.next_lsn = lsn + 1
            if payload:
                recs.append(TxRecord.from_bytes(payload))
        return self._events_from(recs)


def merge_streams(changes: list[TxChange]) -> list[TxChange]:
    """Order changes from multiple LS streams by commit version (the
    cross-LS sequencer analog; ties break by tx id for determinism)."""
    return sorted(changes, key=lambda c: (c.commit_version, c.tx_id))
