// Native micro-block column codecs.
//
// Reference surface: the per-column micro-block encodings and their SIMD
// decoders (storage/blocksstable/encoding/, cs_encoding/ — e.g.
// ob_dict_decoder_simd.cpp, integer FOR/delta packs). The rebuild keeps the
// same idea — immutable columnar blocks, per-column lightweight encodings,
// decode straight into contiguous buffers the engine ships to the device —
// but with a deliberately byte-aligned format so the decode loop is a
// memcpy-shaped widening add that autovectorizes, and so the numpy fallback
// (oceanbase_tpu/storage/encoding.py) can implement the identical layout.
//
// Encodings (enc byte in the block's column descriptor):
//   RAW   0: verbatim little-endian fixed-width values
//   CONST 1: single value, all rows equal
//   FOR   2: frame-of-reference: i64 min, u8 byte-width in {1,2,4,8},
//            then (v - min) packed at that width (unsigned)
//   RLE   3: u32 run count, then runs of {u32 length, value}
//
// All functions are C ABI for ctypes. Sizes are int64. Return value < 0
// means error (insufficient capacity / malformed input).

#include <cstdint>
#include <cstring>

extern "C" {

// ---------------------------------------------------------------- crc32
// zlib-polynomial CRC32 (reflected, 0xEDB88320), byte-at-a-time table.
// Matches Python's zlib.crc32 so both codec implementations agree.
static uint32_t g_crc_table[256];
static bool g_crc_init = false;

static void crc_init() {
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = i;
    for (int k = 0; k < 8; ++k) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    g_crc_table[i] = c;
  }
  g_crc_init = true;
}

uint32_t ob_crc32(const uint8_t* buf, int64_t len, uint32_t seed) {
  if (!g_crc_init) crc_init();
  uint32_t c = seed ^ 0xFFFFFFFFu;
  for (int64_t i = 0; i < len; ++i)
    c = g_crc_table[(c ^ buf[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

// ---------------------------------------------------------------- FOR
// Pack (v - min) at byte width w. Caller chose w so the deltas fit.

#define DEF_FOR_ENCODE(T)                                                     \
  int64_t ob_for_encode_##T(const T* in, int64_t n, int64_t min_v, int width, \
                            uint8_t* out, int64_t cap) {                      \
    if (cap < n * width) return -1;                                           \
    switch (width) {                                                          \
      case 1:                                                                 \
        for (int64_t i = 0; i < n; ++i)                                       \
          out[i] = (uint8_t)((uint64_t)((int64_t)in[i] - min_v));             \
        break;                                                                \
      case 2: {                                                               \
        uint16_t* o = (uint16_t*)out;                                         \
        for (int64_t i = 0; i < n; ++i)                                       \
          o[i] = (uint16_t)((uint64_t)((int64_t)in[i] - min_v));              \
        break;                                                                \
      }                                                                       \
      case 4: {                                                               \
        uint32_t* o = (uint32_t*)out;                                         \
        for (int64_t i = 0; i < n; ++i)                                       \
          o[i] = (uint32_t)((uint64_t)((int64_t)in[i] - min_v));              \
        break;                                                                \
      }                                                                       \
      case 8: {                                                               \
        uint64_t* o = (uint64_t*)out;                                         \
        for (int64_t i = 0; i < n; ++i)                                       \
          o[i] = (uint64_t)((int64_t)in[i] - min_v);                          \
        break;                                                                \
      }                                                                       \
      default:                                                                \
        return -2;                                                            \
    }                                                                         \
    return n * width;                                                         \
  }

#define DEF_FOR_DECODE(T)                                                    \
  int64_t ob_for_decode_##T(const uint8_t* in, int64_t n, int64_t min_v,     \
                            int width, T* out) {                             \
    switch (width) {                                                         \
      case 1:                                                                \
        for (int64_t i = 0; i < n; ++i) out[i] = (T)(min_v + (int64_t)in[i]);\
        break;                                                               \
      case 2: {                                                              \
        const uint16_t* p = (const uint16_t*)in;                             \
        for (int64_t i = 0; i < n; ++i) out[i] = (T)(min_v + (int64_t)p[i]); \
        break;                                                               \
      }                                                                      \
      case 4: {                                                              \
        const uint32_t* p = (const uint32_t*)in;                             \
        for (int64_t i = 0; i < n; ++i) out[i] = (T)(min_v + (int64_t)p[i]); \
        break;                                                               \
      }                                                                      \
      case 8: {                                                              \
        const uint64_t* p = (const uint64_t*)in;                             \
        for (int64_t i = 0; i < n; ++i)                                      \
          out[i] = (T)(min_v + (int64_t)p[i]);                               \
        break;                                                               \
      }                                                                      \
      default:                                                               \
        return -2;                                                           \
    }                                                                        \
    return n;                                                                \
  }

DEF_FOR_ENCODE(int8_t)
DEF_FOR_ENCODE(int16_t)
DEF_FOR_ENCODE(int32_t)
DEF_FOR_ENCODE(int64_t)
DEF_FOR_DECODE(int8_t)
DEF_FOR_DECODE(int16_t)
DEF_FOR_DECODE(int32_t)
DEF_FOR_DECODE(int64_t)

// ---------------------------------------------------------------- RLE
// Layout: u32 nruns, then nruns * {u32 run_len, T value}.

#define DEF_RLE(T)                                                            \
  int64_t ob_rle_encode_##T(const T* in, int64_t n, uint8_t* out,             \
                            int64_t cap) {                                    \
    if (cap < 4) return -1;                                                   \
    int64_t pos = 4;                                                          \
    uint32_t nruns = 0;                                                       \
    int64_t i = 0;                                                            \
    while (i < n) {                                                           \
      T v = in[i];                                                            \
      int64_t j = i + 1;                                                      \
      while (j < n && in[j] == v) ++j;                                        \
      if (pos + 4 + (int64_t)sizeof(T) > cap) return -1;                      \
      uint32_t run = (uint32_t)(j - i);                                       \
      memcpy(out + pos, &run, 4);                                             \
      memcpy(out + pos + 4, &v, sizeof(T));                                   \
      pos += 4 + sizeof(T);                                                   \
      ++nruns;                                                                \
      i = j;                                                                  \
    }                                                                         \
    memcpy(out, &nruns, 4);                                                   \
    return pos;                                                               \
  }                                                                           \
  int64_t ob_rle_decode_##T(const uint8_t* in, int64_t in_len, T* out,        \
                            int64_t out_n) {                                  \
    if (in_len < 4) return -1;                                                \
    uint32_t nruns;                                                           \
    memcpy(&nruns, in, 4);                                                    \
    int64_t pos = 4, written = 0;                                             \
    for (uint32_t r = 0; r < nruns; ++r) {                                    \
      if (pos + 4 + (int64_t)sizeof(T) > in_len) return -1;                   \
      uint32_t run;                                                           \
      T v;                                                                    \
      memcpy(&run, in + pos, 4);                                              \
      memcpy(&v, in + pos + 4, sizeof(T));                                    \
      pos += 4 + sizeof(T);                                                   \
      if (written + run > out_n) return -1;                                   \
      for (uint32_t k = 0; k < run; ++k) out[written + k] = v;                \
      written += run;                                                         \
    }                                                                         \
    return written;                                                           \
  }

DEF_RLE(int8_t)
DEF_RLE(int16_t)
DEF_RLE(int32_t)
DEF_RLE(int64_t)

// ------------------------------------------------------- analysis helper
// One pass over an integer column: min, max, number of runs. The block
// writer uses this to choose RAW vs CONST vs FOR vs RLE without multiple
// scans from Python.
void ob_analyze_i64(const int64_t* in, int64_t n, int64_t* out_min,
                    int64_t* out_max, int64_t* out_runs) {
  if (n == 0) {
    *out_min = 0;
    *out_max = 0;
    *out_runs = 0;
    return;
  }
  int64_t mn = in[0], mx = in[0], runs = 1;
  for (int64_t i = 1; i < n; ++i) {
    int64_t v = in[i];
    if (v < mn) mn = v;
    if (v > mx) mx = v;
    runs += (v != in[i - 1]);
  }
  *out_min = mn;
  *out_max = mx;
  *out_runs = runs;
}

}  // extern "C"
