"""Native (C++) runtime components, loaded via ctypes.

The reference implements its storage codecs, log engine and allocators in
C++ (storage/blocksstable/encoding, logservice/palf). Here the native hot
paths live in small C++ translation units compiled on first use with the
baked-in toolchain (g++) into shared objects cached next to the sources;
every native entry point has a numpy fallback so the framework still works
where no compiler is available (pure wheel installs, sandboxes).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_LOCK = threading.Lock()
_LIBS: dict[str, ctypes.CDLL | None] = {}


def _build(name: str) -> str | None:
    src = os.path.join(_DIR, f"{name}.cpp")
    so = os.path.join(_DIR, f"_{name}.so")
    if os.path.exists(so) and os.path.getmtime(so) >= os.path.getmtime(src):
        return so
    tmp = so + f".tmp.{os.getpid()}"
    cmd = ["g++", "-O3", "-march=native", "-shared", "-fPIC", "-std=c++17",
           "-o", tmp, src]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, so)  # atomic: concurrent builders race benignly
        return so
    except (subprocess.SubprocessError, OSError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None


def load(name: str) -> ctypes.CDLL | None:
    """Load (building if needed) the shared object for native/<name>.cpp.

    Returns None when no toolchain is available; callers fall back to numpy.
    Set OCEANBASE_TPU_NO_NATIVE=1 to force fallbacks (used by tests to cover
    both paths).
    """
    if os.environ.get("OCEANBASE_TPU_NO_NATIVE"):
        return None
    with _LOCK:
        if name not in _LIBS:
            so = _build(name)
            _LIBS[name] = ctypes.CDLL(so) if so else None
        return _LIBS[name]
